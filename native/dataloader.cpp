// Native batch-assembly backend for ddp_practice_tpu.data.
//
// The reference's input pipeline hot path is torch DataLoader worker
// processes doing fancy-indexed batch collation + pinned-memory copies
// (origin_main.py:91-107). The TPU-native equivalent keeps the dataset as
// one contiguous host array and assembles each (already-sharded) batch with
// a multithreaded strided gather; the result feeds
// jax.make_array_from_process_local_data, which overlaps the H2D transfer.
//
// Exposed as a tiny C ABI consumed via ctypes (no pybind11 in this image).
// Shuffling deliberately stays in Python/NumPy so the epoch order is
// bit-identical across the native and pure-Python backends.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Dataset {
  const uint8_t* images;  // (n, sample_elems) row-major, elem_bytes each
  const int32_t* labels;  // (n,)
  int64_t n;
  int64_t row_bytes;      // sample_elems * elem_bytes
};

// Byte-level rows: the same gather serves fp32 (MNIST/CIFAR in RAM) and
// uint8 (ImageNet-scale memmap) storage; for a memmapped corpus the
// memcpy's source reads fault pages in from disk, so this doubles as the
// streaming read path.
void gather_range(const Dataset& ds, const int64_t* indices, int64_t begin,
                  int64_t end, uint8_t* out_images, int32_t* out_labels,
                  std::atomic<bool>* oob) {
  for (int64_t i = begin; i < end; ++i) {
    int64_t src = indices[i];
    if (src < 0) src += ds.n;      // numpy-style negative wrapping
    if (src < 0 || src >= ds.n) {  // then numpy's IndexError contract
      oob->store(true, std::memory_order_relaxed);
      return;
    }
    std::memcpy(out_images + i * ds.row_bytes,
                ds.images + src * ds.row_bytes,
                static_cast<size_t>(ds.row_bytes));
    out_labels[i] = ds.labels[src];
  }
}

}  // namespace

extern "C" {

// Wraps caller-owned arrays; caller guarantees their lifetime.
// elem_bytes is the per-element width (4 for fp32, 1 for uint8).
void* dl_create(const void* images, const int32_t* labels, int64_t n,
                int64_t sample_elems, int32_t elem_bytes) {
  return new Dataset{static_cast<const uint8_t*>(images), labels, n,
                     sample_elems * elem_bytes};
}

void dl_destroy(void* handle) { delete static_cast<Dataset*>(handle); }

// Gather `count` samples by index into out buffers, using up to
// `num_threads` threads (<=0 means hardware concurrency). Returns 0 on
// success, -1 if any index is out of [0, n) — mirroring the numpy
// backend's IndexError instead of reading out-of-bounds memory.
int32_t dl_gather(void* handle, const int64_t* indices, int64_t count,
                  void* out_images_v, int32_t* out_labels,
                  int32_t num_threads) {
  uint8_t* out_images = static_cast<uint8_t*>(out_images_v);
  const Dataset& ds = *static_cast<Dataset*>(handle);
  std::atomic<bool> oob{false};
  int64_t nthreads = num_threads > 0
                         ? num_threads
                         : static_cast<int64_t>(std::thread::hardware_concurrency());
  if (nthreads < 1) nthreads = 1;
  // Small batches: threading overhead dominates; stay single-threaded.
  const int64_t kMinPerThread = 64;
  if (count / kMinPerThread < nthreads) nthreads = count / kMinPerThread;
  if (nthreads <= 1) {
    gather_range(ds, indices, 0, count, out_images, out_labels, &oob);
    return oob.load() ? -1 : 0;
  }
  std::vector<std::thread> workers;
  workers.reserve(nthreads);
  const int64_t per = (count + nthreads - 1) / nthreads;
  for (int64_t t = 0; t < nthreads; ++t) {
    const int64_t begin = t * per;
    const int64_t end = begin + per < count ? begin + per : count;
    if (begin >= end) break;
    workers.emplace_back(gather_range, std::cref(ds), indices, begin, end,
                         out_images, out_labels, &oob);
  }
  for (auto& w : workers) w.join();
  return oob.load() ? -1 : 0;
}

int32_t dl_version() { return 3; }

}  // extern "C"
