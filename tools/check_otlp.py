"""OTLP-JSON trace export validator (utils/trace.py ``to_otlp``).

The OTLP export exists so a real collector (Jaeger / Tempo / any
OTLP/HTTP endpoint) can ingest the serve timeline — which means the
artifact must be shape-correct down to the proto3-JSON conventions an
actual collector enforces, not just "some JSON with spans in it". This
validator makes that a checkable contract, used two ways:

- from tests: ``from tools.check_otlp import validate_otlp`` — returns
  a list of error strings (empty = clean);
- as a CLI::

      python tools/check_otlp.py export.json [--chrome trace.json] [--json]
      python tools/check_otlp.py capture_dir/ [--json]

  exit 0 clean, 1 invalid, 2 unreadable/unparseable input.

A DIRECTORY argument is a push-capture: what the stub OTLP collector
(utils/telemetry.py StubOtlpCollector) wrote — one JSON payload file
per received POST, duplicates included (the pusher is at-least-once:
a delivered-but-response-lost batch is retried and arrives twice).
Batches are deduped by their ``ddp.push.batch_id`` resource attribute
(keep FIRST, the receiver's half of the contract) and the surviving
payloads merge into one export that must validate exactly like a
single-file export — in particular, spanIds must be unique ACROSS the
whole merged capture, which is what pins the pusher's
each-span-in-exactly-one-batch drain invariant.

Shape checks (each one a real way to lose data inside a collector):

- top level is ``{"resourceSpans": [...]}`` with resource/scopeSpans/
  spans nesting;
- **id hygiene**: traceId is 32 lowercase hex chars, spanId is 16,
  neither all-zero (collectors DROP zero-id spans silently), spanIds
  unique within the export;
- **parent linkage**: every parentSpanId resolves to a spanId in the
  SAME trace — an orphaned parent renders as a broken trace tree;
- **time sanity**: start/end are digit-strings (proto3 JSON int64),
  end >= start;
- **names and attributes**: non-empty span names; attributes are
  KeyValue lists (``{"key": ..., "value": {<type>Value: ...}}``).

Round-trip mode (``--chrome chrome_trace.json``): the OTLP export and
the Chrome export come from the SAME recorder, so the set of request
trace_ids must match — every span's ``ddp.trace_id`` attribute against
the Chrome events' ``args.trace_id``. A mismatch means one exporter
filtered what the other kept (the bug this mode exists to catch:
sampling decisions applied to one export path but not the other).
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

_HEX = set("0123456789abcdef")


def _is_hex(s, width: int) -> bool:
    return (isinstance(s, str) and len(s) == width
            and set(s) <= _HEX and set(s) != {"0"})


def _attr_errors(attrs, where: str) -> List[str]:
    errors = []
    if not isinstance(attrs, list):
        return [f"{where}: attributes must be a KeyValue list"]
    for j, kv in enumerate(attrs):
        if not (isinstance(kv, dict) and isinstance(kv.get("key"), str)
                and isinstance(kv.get("value"), dict)):
            errors.append(
                f"{where}: attribute {j} is not a "
                "{key, value: {...}} pair")
            continue
        val = kv["value"]
        if not any(k in val for k in (
                "stringValue", "boolValue", "intValue", "doubleValue",
                "arrayValue", "kvlistValue", "bytesValue")):
            errors.append(
                f"{where}: attribute {kv['key']!r} has no typed value")
        if "intValue" in val and not isinstance(val["intValue"], str):
            # proto3 JSON renders int64 as a STRING; a bare JSON number
            # silently loses precision past 2^53 inside collectors
            errors.append(
                f"{where}: attribute {kv['key']!r} intValue must be a "
                "string (proto3 JSON int64)")
    return errors


def attrs_dict(span: dict) -> dict:
    """KeyValue list -> plain dict (first value field wins)."""
    out = {}
    for kv in span.get("attributes") or []:
        if not isinstance(kv, dict):
            continue
        val = kv.get("value")
        if isinstance(val, dict) and val:
            out[kv.get("key")] = next(iter(val.values()))
    return out


def iter_spans(export: dict):
    """Flatten resourceSpans -> scopeSpans -> spans."""
    for rs in export.get("resourceSpans", []) or []:
        if not isinstance(rs, dict):
            continue
        for ss in rs.get("scopeSpans", []) or []:
            if not isinstance(ss, dict):
                continue
            for span in ss.get("spans", []) or []:
                if isinstance(span, dict):
                    yield span


def validate_otlp(export) -> List[str]:
    """Validate a parsed OTLP-JSON export; return error strings."""
    errors: List[str] = []
    if not isinstance(export, dict) or not isinstance(
            export.get("resourceSpans"), list):
        return ["top level must be an object with a 'resourceSpans' list"]
    for ri, rs in enumerate(export["resourceSpans"]):
        if not isinstance(rs, dict):
            errors.append(f"resourceSpans[{ri}]: not an object")
            continue
        res = rs.get("resource")
        if not isinstance(res, dict):
            errors.append(f"resourceSpans[{ri}]: missing resource")
        else:
            errors += _attr_errors(
                res.get("attributes", []),
                f"resourceSpans[{ri}].resource")
        if not isinstance(rs.get("scopeSpans"), list):
            errors.append(f"resourceSpans[{ri}]: missing scopeSpans list")
    spans = list(iter_spans(export))
    seen_sids = {}
    by_trace = {}
    for i, span in enumerate(spans):
        name = span.get("name")
        where = f"span {i} ({name!r})"
        if not isinstance(name, str) or not name:
            errors.append(f"span {i}: missing/empty name")
        tid = span.get("traceId")
        sid = span.get("spanId")
        if not _is_hex(tid, 32):
            errors.append(
                f"{where}: traceId must be 32 lowercase hex chars "
                f"(non-zero), got {tid!r}")
            continue
        if not _is_hex(sid, 16):
            errors.append(
                f"{where}: spanId must be 16 lowercase hex chars "
                f"(non-zero), got {sid!r}")
            continue
        if sid in seen_sids:
            errors.append(
                f"{where}: duplicate spanId {sid} "
                f"(also span {seen_sids[sid]}) — collectors keep one")
        seen_sids[sid] = i
        by_trace.setdefault(tid, set()).add(sid)
        t0, t1 = span.get("startTimeUnixNano"), span.get("endTimeUnixNano")
        for label, t in (("startTimeUnixNano", t0),
                         ("endTimeUnixNano", t1)):
            if not (isinstance(t, str) and t.isdigit()):
                errors.append(
                    f"{where}: {label} must be a digit-string "
                    f"(proto3 JSON int64), got {t!r}")
        if (isinstance(t0, str) and isinstance(t1, str)
                and t0.isdigit() and t1.isdigit() and int(t1) < int(t0)):
            errors.append(
                f"{where}: ends before it starts ({t0} -> {t1})")
        errors += _attr_errors(span.get("attributes", []), where)
    # parent linkage: second pass, after every spanId is known
    for i, span in enumerate(spans):
        parent = span.get("parentSpanId")
        if parent is None:
            continue
        tid = span.get("traceId")
        if parent not in by_trace.get(tid, ()):
            errors.append(
                f"span {i} ({span.get('name')!r}): parentSpanId "
                f"{parent!r} resolves to no span in trace {tid!r} — "
                "orphaned subtree")
    return errors


def crosscheck_chrome(export: dict, chrome: dict) -> List[str]:
    """Same-recorder round-trip: request trace_id sets must match.

    OTLP side: each span's ``ddp.trace_id`` attribute. Chrome side:
    every event's ``args.trace_id``. Events without a trace_id
    (decode_burst lanes, clock_offset instants) are infrastructure and
    intentionally absent from OTLP — only the request-tagged population
    is compared."""
    errors: List[str] = []
    otlp_tids = set()
    for span in iter_spans(export):
        t = attrs_dict(span).get("ddp.trace_id")
        if t is not None:
            otlp_tids.add(str(t))
    chrome_tids = set()
    for ev in chrome.get("traceEvents", []) or []:
        if not isinstance(ev, dict) or ev.get("ph") == "M":
            continue
        t = (ev.get("args") or {}).get("trace_id")
        if t is not None:
            chrome_tids.add(str(t))
    only_chrome = sorted(chrome_tids - otlp_tids)
    only_otlp = sorted(otlp_tids - chrome_tids)
    if only_chrome:
        errors.append(
            f"round-trip: {len(only_chrome)} trace_id(s) in the Chrome "
            f"export but not in OTLP (first: {only_chrome[:5]}) — the "
            "OTLP path filtered spans the recorder kept")
    if only_otlp:
        errors.append(
            f"round-trip: {len(only_otlp)} trace_id(s) in OTLP but not "
            f"in the Chrome export (first: {only_otlp[:5]}) — the OTLP "
            "path invented or resurrected spans")
    return errors


def push_batch_id(export) -> str:
    """The ``ddp.push.batch_id`` resource attribute, or None.

    Stamped by the pusher (utils/telemetry.py OtlpPusher.collect) into
    every batch's resource attributes; the at-least-once retry loop can
    deliver the same batch twice, and this id is what lets a receiver
    (or this tool's directory mode) keep exactly one copy."""
    if not isinstance(export, dict):
        return None
    for rs in export.get("resourceSpans") or []:
        if not isinstance(rs, dict):
            continue
        res = rs.get("resource")
        if not isinstance(res, dict):
            continue
        for kv in res.get("attributes") or []:
            if isinstance(kv, dict) and kv.get("key") == "ddp.push.batch_id":
                val = kv.get("value")
                if isinstance(val, dict) and "stringValue" in val:
                    return str(val["stringValue"])
    return None


def load_push_capture(dirpath: str):
    """Load a push-capture directory into one deduped, merged export.

    Reads every ``*.json`` payload (sorted by filename — the stub
    collector numbers them in arrival order), drops whole batches whose
    ``ddp.push.batch_id`` was already seen (keep FIRST), and
    concatenates the survivors' resourceSpans into a single export.
    Returns ``(export, info)`` where info counts files / unique batches
    / duplicates and carries shape errors for payloads that were valid
    JSON but not OTLP-shaped. Raises OSError / json.JSONDecodeError for
    unreadable input, same as the single-file path."""
    files = sorted(n for n in os.listdir(dirpath) if n.endswith(".json"))
    if not files:
        raise OSError(f"no *.json batch payloads in {dirpath}")
    merged = {"resourceSpans": []}
    seen = set()
    duplicates = 0
    shape_errors: List[str] = []
    for name in files:
        with open(os.path.join(dirpath, name)) as f:
            export = json.load(f)
        bid = push_batch_id(export)
        if bid is not None:
            if bid in seen:
                duplicates += 1
                continue
            seen.add(bid)
        if not (isinstance(export, dict)
                and isinstance(export.get("resourceSpans"), list)):
            shape_errors.append(
                f"{name}: payload is not an OTLP export "
                "(no 'resourceSpans' list)")
            continue
        if bid is None:
            shape_errors.append(
                f"{name}: batch carries no ddp.push.batch_id resource "
                "attribute — a retried delivery of it could never be "
                "deduped")
        merged["resourceSpans"].extend(export["resourceSpans"])
    info = {"files": len(files), "unique_batches": len(seen),
            "duplicate_batches": duplicates, "errors": shape_errors}
    return merged, info


def summarize(export: dict) -> dict:
    spans = list(iter_spans(export))
    traces = {s.get("traceId") for s in spans}
    roots = [s for s in spans if "parentSpanId" not in s]
    return {"spans": len(spans), "traces": len(traces),
            "roots": len(roots)}


def main(argv=None) -> int:
    args = sys.argv[1:] if argv is None else list(argv)
    if not args or args[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    chrome_path = None
    as_json = False
    paths = []
    it = iter(args)
    for a in it:
        if a == "--chrome":
            try:
                chrome_path = next(it)
            except StopIteration:
                print("--chrome wants a Chrome trace JSON path")
                return 2
        elif a == "--json":
            as_json = True
        else:
            paths.append(a)
    if not paths:
        print("no OTLP export files given")
        return 2
    chrome = None
    if chrome_path is not None:
        try:
            with open(chrome_path) as f:
                chrome = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"{chrome_path}: UNREADABLE chrome trace — {e}")
            return 2
    rc = 0
    report = []
    for path in paths:
        cap = None
        try:
            if os.path.isdir(path):
                export, cap = load_push_capture(path)
            else:
                with open(path) as f:
                    export = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"{path}: UNREADABLE — {e}")
            return 2
        errors = (list(cap["errors"]) if cap else []) + validate_otlp(export)
        if chrome is not None:
            errors += crosscheck_chrome(export, chrome)
        s = summarize(export)
        entry = {"path": path, "ok": not errors, "errors": errors, **s}
        if cap is not None:
            entry.update(files=cap["files"],
                         unique_batches=cap["unique_batches"],
                         duplicate_batches=cap["duplicate_batches"])
        report.append(entry)
        batched = ""
        if cap is not None:
            batched = (f" [{cap['unique_batches']} batch(es) from "
                       f"{cap['files']} payload(s), "
                       f"{cap['duplicate_batches']} duplicate(s)]")
        if errors:
            rc = 1
            print(f"{path}: INVALID ({len(errors)} error(s); "
                  f"{s['spans']} spans){batched}")
            for e in errors[:20]:
                print(f"  - {e}")
            if len(errors) > 20:
                print(f"  ... and {len(errors) - 20} more")
        else:
            extra = " (round-trip vs chrome OK)" if chrome is not None \
                else ""
            print(f"{path}: OK — {s['spans']} spans across "
                  f"{s['traces']} trace(s), {s['roots']} root(s)"
                  f"{batched}{extra}")
    if as_json:
        print(json.dumps(report, indent=2))
    return rc


if __name__ == "__main__":
    sys.exit(main())
