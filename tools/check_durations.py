"""Tier-1 duration-ledger auditor (tests/conftest.py sessionfinish).

The tier-1 gate runs under ``timeout -k 10 870`` — a hard ceiling that
TRUNCATES a too-slow suite silently (fewer dots, no failure). Every
pytest session writes a per-test duration ledger at exit
(``DDP_T1_DURATIONS_OUT``, default /tmp/_t1_durations.json); this tool
audits it offline, the twin of the in-run sentinel
tests/test_zzz_t1_budget.py::

    python tools/check_durations.py [/tmp/_t1_durations.json]
        [--budget-s 870] [--top 10] [--json]
        [--strict-slow] [--noise-margin S]

Exit codes: 0 the run fits its budget, 1 it projects past the budget,
2 unreadable/shape-invalid ledger.

What it checks:

- **projection**: measured wall time (or summed durations padded 5% +
  45 s when wall is absent) against the budget — the "will the NEXT
  run be truncated" question;
- **slow-marker hygiene** (WARNINGs): any test over 10 s inside a
  ``not slow`` run belongs behind ``@pytest.mark.slow`` (the repo's
  marker contract) — printed per offender so the fix is a one-line
  diff, escalated to exit 1 under ``--strict-slow``. ``--noise-margin
  S`` raises the threshold to 10+S seconds for the STRICT verdict
  only (tier-1 runs with ``--strict-slow --noise-margin 2.0``: the
  1-core CI box jitters a borderline 10.5 s test across the line run
  to run, and a gate that flaps is a gate that gets ignored — the
  plain warning still fires at 10 s so the drift stays visible).
"""

from __future__ import annotations

import json
import sys
from typing import List

DEFAULT_LEDGER = "/tmp/_t1_durations.json"
DEFAULT_BUDGET_S = 870.0
SLOW_MARK_S = 10.0     # pytest.ini: >10 s individually => mark slow
OVERHEAD_FACTOR = 1.05
TAIL_ALLOWANCE_S = 45.0


def audit(ledger: dict, budget_s: float = DEFAULT_BUDGET_S,
          noise_margin_s: float = 0.0):
    """-> (errors, warnings, report) for one parsed ledger object.
    Warnings over SLOW_MARK_S + noise_margin_s carry a ``strict``
    prefix marker via the returned `strict_warnings` list in the
    report — --strict-slow fails on those only, so CI jitter inside
    the margin can't flap the gate."""
    errors: List[str] = []
    warnings: List[str] = []
    strict_warnings: List[str] = []
    if not isinstance(ledger, dict) or not isinstance(
            ledger.get("tests"), dict):
        return (["ledger must be an object with a 'tests' mapping"],
                [], {})
    tests = {
        k: float(v) for k, v in ledger["tests"].items()
        if isinstance(v, (int, float))
    }
    total = sum(tests.values())
    wall = ledger.get("wall_s")
    projected = (float(wall) if isinstance(wall, (int, float))
                 else total * OVERHEAD_FACTOR + TAIL_ALLOWANCE_S)
    markexpr = str(ledger.get("markexpr", ""))
    report = {
        "tests": len(tests), "sum_s": round(total, 1),
        "wall_s": wall, "projected_s": round(projected, 1),
        "budget_s": budget_s, "markexpr": markexpr,
    }
    if projected >= budget_s:
        errors.append(
            f"run projects to {projected:.0f}s against the hard "
            f"{budget_s:.0f}s timeout — the wrapper truncates "
            f"silently; mark the slowest tests @pytest.mark.slow"
        )
    if "not slow" in markexpr:
        for nodeid, d in sorted(tests.items(), key=lambda kv: -kv[1]):
            if d > SLOW_MARK_S:
                msg = (
                    f"{nodeid} took {d:.1f}s inside a 'not slow' run "
                    f"(> {SLOW_MARK_S:.0f}s) — mark it "
                    f"@pytest.mark.slow"
                )
                warnings.append(msg)
                if d > SLOW_MARK_S + noise_margin_s:
                    strict_warnings.append(msg)
    report["strict_warnings"] = strict_warnings
    return errors, warnings, report


def main(argv=None) -> int:
    args = sys.argv[1:] if argv is None else list(argv)
    if args and args[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    budget_s = DEFAULT_BUDGET_S
    top = 10
    as_json = False
    strict_slow = False
    noise_margin = 0.0
    path = None
    it = iter(args)
    for a in it:
        if a == "--budget-s":
            try:
                budget_s = float(next(it))
            except (StopIteration, ValueError):
                print("--budget-s wants a number (seconds)")
                return 2
        elif a == "--noise-margin":
            try:
                noise_margin = float(next(it))
            except (StopIteration, ValueError):
                print("--noise-margin wants a number (seconds)")
                return 2
        elif a == "--top":
            try:
                top = int(next(it))
            except (StopIteration, ValueError):
                print("--top wants an integer")
                return 2
        elif a == "--json":
            as_json = True
        elif a == "--strict-slow":
            strict_slow = True
        elif path is None:
            path = a
        else:
            print(f"unexpected argument {a!r}")
            return 2
    path = path or DEFAULT_LEDGER
    try:
        with open(path) as f:
            ledger = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"{path}: UNREADABLE — {e}")
        return 2
    errors, warnings, report = audit(ledger, budget_s, noise_margin)
    if not report:
        print(f"{path}: INVALID — {errors[0]}")
        return 2
    rc = 1 if errors or (strict_slow
                         and report["strict_warnings"]) else 0
    verdict = "OVER BUDGET" if errors else "OK"
    print(f"{path}: {verdict} — {report['tests']} tests, "
          f"projected {report['projected_s']}s of "
          f"{report['budget_s']}s budget "
          f"(markexpr: {report['markexpr'] or 'none'})")
    for e in errors:
        print(f"  ERROR: {e}")
    for w in warnings:
        print(f"  WARNING: {w}")
    tests = ledger.get("tests", {})
    slowest = sorted(
        ((k, v) for k, v in tests.items()
         if isinstance(v, (int, float))),
        key=lambda kv: -kv[1])[:top]
    if slowest and not as_json:
        print("  slowest:")
        for n, d in slowest:
            print(f"    {d:7.2f}s  {n}")
    if as_json:
        print(json.dumps({**report, "errors": errors,
                          "warnings": warnings,
                          "slowest": slowest}, indent=2))
    return rc


if __name__ == "__main__":
    sys.exit(main())
