"""Offline exactly-once audit over telemetry chunk lines.

The router's streaming plane (serve/router.py TokenStream) claims an
exactly-once contract: per request, token chunks reach the consumer
with contiguous sequence numbers, no duplicated and no missing token
offsets, resume markers at failover splices, and exactly one typed
terminal event. The chaos tests assert that IN-process; this tool
re-derives it from the telemetry JSONL alone — the artifact a
production incident would actually have in hand:

    python tools/check_stream.py telemetry.jsonl
    python tools/check_stream.py --json run.jsonl

Audited lines are ``{"kind": "chunk", ...}`` as written by
Router._stream_emit (consumer-side stream events, ``event`` =
tokens/resumed/end) or by Scheduler._emit_chunk (single-replica
serving, ``final`` marks the terminal). Per trace_id the checks are:

- ``seq`` contiguous from 0 — a duplicate seq is a replayed delivery,
  a hole is a lost one;
- token-offset continuity — every token-carrying line must start
  exactly where the previous one ended (``start`` == tokens delivered
  so far): an overlap means the consumer saw tokens twice, a gap means
  it silently missed some;
- exactly ONE terminal marker, and nothing after it — a stream that
  ends twice (or keeps emitting past its end) broke the close
  contract; a stream with no terminal at all ended in silence, the
  exact failure mode the typed ``end`` event exists to prevent.

exit 0 = every stream holds the contract; 1 = at least one violation;
2 = input unreadable/malformed — a broken audit must be
distinguishable from a broken stream (same convention as
tools/check_bench.py / check_slo.py).

``--sse`` audits the OTHER side of the wire: a JSONL capture of SSE
frames as a socket consumer actually parsed them (one line per frame:
``{"stream": key, "id": int, "event": kind, "data": {...}}`` — the
shape serve/frontdoor.py `sse_request` returns, which the bench and
the socket tests dump verbatim). The frames are mapped onto the same
chunk-line schema (wire ``id`` IS the seq, ``data.start``/``tokens``
are the offsets) and judged by the identical rules — the front door's
claim is precisely that the wire consumer sees the in-process
contract, so the wire capture must pass the in-process audit.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple

OK, VIOLATION, UNREADABLE = 0, 1, 2


def _is_terminal(line: dict) -> bool:
    return line.get("event") == "end" or bool(line.get("final"))


def _carries_tokens(line: dict) -> bool:
    # router "resumed"/"end" events carry n=0; scheduler final chunks
    # may carry a tail. Offset continuity is judged only where tokens
    # actually flowed.
    return int(line.get("n", 0)) > 0


def audit_stream(lines: List[dict]) -> List[str]:
    """Violations for ONE trace_id's chunk lines (empty = contract
    holds). `lines` must be in file order — the delivery order."""
    problems: List[str] = []
    seen_seq = set()
    expected_seq = 0
    delivered = 0
    ended_at = None
    for ln in lines:
        seq = ln.get("seq")
        if not isinstance(seq, int):
            problems.append(f"line without integer seq: {ln!r}")
            continue
        if seq in seen_seq:
            problems.append(f"duplicate seq {seq}")
        elif seq != expected_seq:
            problems.append(
                f"seq jumped to {seq}, expected {expected_seq}"
            )
            expected_seq = seq + 1
        else:
            expected_seq += 1
        seen_seq.add(seq)
        if ended_at is not None:
            problems.append(
                f"seq {seq} emitted after terminal seq {ended_at}"
            )
        if _carries_tokens(ln):
            start = int(ln.get("start", 0))
            n = int(ln["n"])
            if start < delivered:
                problems.append(
                    f"seq {seq}: tokens overlap — start {start} "
                    f"below delivered {delivered} (duplicate delivery)"
                )
            elif start > delivered:
                problems.append(
                    f"seq {seq}: token gap — start {start} above "
                    f"delivered {delivered} (missing delivery)"
                )
            delivered = max(delivered, start + n)
        if _is_terminal(ln):
            if ended_at is not None:
                problems.append(
                    f"second terminal at seq {seq} "
                    f"(first at {ended_at})"
                )
            else:
                ended_at = seq
    if ended_at is None:
        problems.append("no terminal marker — the stream ended in "
                        "silence")
    return problems


def stream_verdict(lines: List[dict]) -> Tuple[bool, dict]:
    """(ok, report) over every chunk line in a telemetry run — the
    pure function the CLI and the artifact tests share. Non-chunk
    lines are ignored (the telemetry stream interleaves flight/alert/
    watchdog kinds on purpose)."""
    streams: Dict[str, List[dict]] = {}
    for ln in lines:
        if ln.get("kind") != "chunk":
            continue
        key = ln.get("trace_id") or f"rid:{ln.get('rid')}"
        streams.setdefault(key, []).append(ln)
    violations: Dict[str, List[str]] = {}
    tokens_total = 0
    for key, chunk_lines in streams.items():
        probs = audit_stream(chunk_lines)
        if probs:
            violations[key] = probs
        tokens_total += sum(int(ln.get("n", 0)) for ln in chunk_lines)
    report = {
        "streams": len(streams),
        "tokens": tokens_total,
        "violations": violations,
    }
    return (len(streams) > 0 and not violations), report


def sse_to_chunks(records: List[dict]) -> List[dict]:
    """Captured SSE frames -> chunk-line schema, losslessly enough for
    the audit: wire id -> seq, event name -> event, payload start/
    token-count/status carried through. A frame whose ``data`` is not
    an object (malformed payload on the wire) maps to a line with no
    seq — audit_stream flags it rather than this converter hiding it."""
    out: List[dict] = []
    for rec in records:
        data = rec.get("data")
        if not isinstance(data, dict):
            data = {}
        key = (rec.get("stream")
               or data.get("trace_id")
               or f"rid:{rec.get('rid')}")
        out.append({
            "kind": "chunk",
            "trace_id": key,
            "seq": rec.get("id"),
            "event": rec.get("event"),
            "start": data.get("start", 0),
            "n": len(data.get("tokens") or ()),
            "status": data.get("status"),
        })
    return out


def load_jsonl(path: str) -> List[dict]:
    out: List[dict] = []
    with open(path) as f:
        for i, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                obj = json.loads(raw)
            except ValueError as e:
                raise ValueError(f"{path}:{i}: bad JSON ({e})") from e
            if not isinstance(obj, dict):
                raise ValueError(f"{path}:{i}: line is not an object")
            out.append(obj)
    return out


def render(source: str, ok: bool, report: dict) -> str:
    lines = [
        f"  {report['streams']} stream(s), "
        f"{report['tokens']} token(s) audited"
    ]
    for key, probs in sorted(report["violations"].items()):
        for p in probs:
            lines.append(f"  VIOLATION  {key}: {p}")
    if report["streams"] == 0:
        lines.append("  VIOLATION  no chunk lines at all — nothing "
                     "streamed (or the wrong file)")
    lines.append(f"{source}: "
                 + ("STREAMS OK" if ok else "STREAM CONTRACT BROKEN"))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "check_stream",
        description="audit telemetry JSONL chunk lines for the "
                    "exactly-once streaming contract (contiguous seq, "
                    "no duplicate/missing tokens, one typed terminal "
                    "per stream)",
    )
    p.add_argument("telemetry", help="telemetry JSONL path (or, with "
                                     "--sse, an SSE frame capture)")
    p.add_argument("--sse", action="store_true",
                   help="input is a wire-side SSE frame capture "
                        "(frontdoor sse_request records), audited "
                        "under the same exactly-once rules")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    try:
        lines = load_jsonl(args.telemetry)
        if args.sse:
            lines = sse_to_chunks(lines)
    except (OSError, ValueError) as e:
        print(f"UNREADABLE — {e}", file=sys.stderr)
        return UNREADABLE
    ok, report = stream_verdict(lines)
    if args.json:
        print(json.dumps({"ok": ok, **report}))
    else:
        print(render(args.telemetry, ok, report))
    return OK if ok else VIOLATION


if __name__ == "__main__":
    sys.exit(main())
