"""Chrome trace-event JSON validator (utils/trace.py exports).

A trace that loads in Perfetto is not necessarily a *correct* trace —
the viewer silently drops unmatched E events, reorders by ts, and
invents rows for unknown pids, so a broken exporter can look fine until
the one debugging session that depends on it. This validator makes the
schema a checkable contract, used two ways:

- from tests: ``from tools.check_traces import validate`` — returns a
  list of error strings (empty = clean), asserted empty by
  tests/test_trace.py on every exported trace;
- as a CLI for eyeballing bench artifacts::

      python tools/check_traces.py t.json [more.json ...]

  prints a per-file verdict + span summary, exit 1 on any error.

Two input forms, auto-detected per file:

- the single-JSON Chrome dump `TraceRecorder.save()` writes at exit;
- a STREAMED telemetry JSONL (utils/telemetry.py TelemetryExporter):
  one kind-tagged event per line. `parse_stream_text` re-assembles the
  trace-shaped lines (meta/span/async/instant; flight/metrics/alert
  lines are telemetry, not trace, and are skipped) into a Chrome trace
  — spans become complete "X" events, so streaming needs no B/E
  matching — and tolerates EXACTLY ONE truncated line at EOF (the line
  a SIGKILL cut mid-write); garbage anywhere else is an error.

Checks (each one a real corruption mode of the exporter):

- top level is ``{"traceEvents": [...]}``; every event has name/ph/pid/
  tid, and (except metadata) a finite ts >= 0;
- **known pids**: every event's pid carries a ``process_name`` metadata
  record — an undeclared pid means an instrumentation site bypassed the
  lane conventions (utils/trace.py label_replica/label_router);
- **matched B/E pairs** per (pid, tid) lane: stack discipline, E names
  match the open B, nothing left open at EOF;
- **monotonic ts** within each lane's B/E stream in file order — a
  violation means the exporter emitted crossing (non-nested) intervals;
- **matched async b/e** per (pid, id): b before e, same name, ts
  ordered, nothing left open;
- only known phases (B E b e i M X C) appear.

FLEET mode (``--fleet [--skew-s S]``): the extra contracts of a MERGED
cross-process timeline (utils/trace.py TraceCollector):

- **cross-process causality**: every router ``dispatch`` instant
  (pid=router, args replica/trace_id) must precede that worker's
  ``queued``/``request`` span start for the same trace_id — within the
  clock-skew tolerance. The tolerance is the trace's own measured skew
  model (the ``clock_offset`` instants the collector stamps, worst
  bound across workers) unless ``--skew-s`` overrides it;
- a killed worker's TRUNCATED stream is tolerated: missing worker-side
  spans are not an error (the spans that did arrive pre-crash still
  validate), only an out-of-order one is;
- dropped-event metadata (``trace_events_dropped``) prints as a WARNING
  either way — a lossy timeline is usable but must say so;
- a SAMPLED timeline (``metadata.sampling``, utils/trace.py
  TraceSampler) is a *partial by policy* timeline: a dispatch whose
  worker lane is absent is exactly what a 1% head rate produces, so
  the missing-lane tolerance above is load-bearing, not charity. The
  sampling header prints as an INFO line — suppressed-by-policy spans
  are an operator choice and must never be confused with
  dropped-by-buffer spans (data loss), which keep their WARNING.
"""

from __future__ import annotations

import json
import sys
from collections import Counter, defaultdict
from typing import List

_KNOWN_PH = {"B", "E", "b", "e", "i", "M", "X", "C"}


def validate(trace) -> List[str]:
    """Validate a parsed Chrome trace object; return error strings."""
    errors: List[str] = []
    if not isinstance(trace, dict) or not isinstance(
            trace.get("traceEvents"), list):
        return ["top level must be an object with a 'traceEvents' list"]
    events = trace["traceEvents"]
    known_pids = {
        ev.get("pid") for ev in events
        if isinstance(ev, dict) and ev.get("ph") == "M"
        and ev.get("name") == "process_name"
    }
    lane_stacks = defaultdict(list)     # (pid, tid) -> [(name, ts)]
    lane_last_ts = {}                   # (pid, tid) -> last B/E ts seen
    async_open = defaultdict(list)      # (pid, id) -> [(name, ts)]
    for i, ev in enumerate(events):
        where = f"event {i}"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        name = ev.get("name")
        if not isinstance(name, str) or not name:
            errors.append(f"{where}: missing/empty name")
            continue
        where = f"event {i} ({ph} {name!r})"
        if ph not in _KNOWN_PH:
            errors.append(f"{where}: unknown ph {ph!r}")
            continue
        if "pid" not in ev or "tid" not in ev:
            errors.append(f"{where}: missing pid/tid")
            continue
        pid, tid = ev["pid"], ev["tid"]
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or not (
                ts == ts and abs(ts) != float("inf")):
            errors.append(f"{where}: ts must be a finite number, got {ts!r}")
            continue
        if ts < 0:
            errors.append(f"{where}: negative ts {ts}")
        if pid not in known_pids:
            errors.append(
                f"{where}: pid {pid!r} has no process_name metadata"
            )
        if ph in ("B", "E"):
            lane = (pid, tid)
            last = lane_last_ts.get(lane)
            if last is not None and ts < last:
                errors.append(
                    f"{where}: lane {lane} ts went backwards "
                    f"({last} -> {ts}) — crossing intervals?"
                )
            lane_last_ts[lane] = ts
            if ph == "B":
                lane_stacks[lane].append((name, ts))
            else:
                if not lane_stacks[lane]:
                    errors.append(f"{where}: E with no open B on {lane}")
                else:
                    open_name, open_ts = lane_stacks[lane].pop()
                    if open_name != name:
                        errors.append(
                            f"{where}: E closes {open_name!r} "
                            f"(B/E name mismatch on {lane})"
                        )
                    elif ts < open_ts:
                        errors.append(
                            f"{where}: span ends before it starts "
                            f"({open_ts} -> {ts})"
                        )
        elif ph in ("b", "e"):
            aid = ev.get("id")
            if aid is None:
                errors.append(f"{where}: async event without id")
                continue
            key = (pid, aid)
            if ph == "b":
                async_open[key].append((name, ts))
            else:
                if not async_open[key]:
                    errors.append(
                        f"{where}: async e with no open b for id {aid!r}"
                    )
                else:
                    open_name, open_ts = async_open[key].pop()
                    if open_name != name:
                        errors.append(
                            f"{where}: async e closes {open_name!r} "
                            f"(name mismatch for id {aid!r})"
                        )
                    elif ts < open_ts:
                        errors.append(
                            f"{where}: async span for id {aid!r} ends "
                            f"before it starts ({open_ts} -> {ts})"
                        )
    for lane, stack in lane_stacks.items():
        if stack:
            errors.append(
                f"lane {lane}: {len(stack)} unclosed B "
                f"(top: {stack[-1][0]!r})"
            )
    for key, stack in async_open.items():
        if stack:
            errors.append(
                f"async id {key[1]!r} (pid {key[0]}): "
                f"{len(stack)} unclosed b"
            )
    return errors


def measured_skew(trace) -> dict:
    """Per-pid worst-case clock-skew bound from the ``clock_offset``
    instants the TraceCollector stamps (empty when the trace carries
    no skew model — a single-process trace, or offsets never measured)."""
    bounds: dict = {}
    for ev in trace.get("traceEvents", []):
        if not (isinstance(ev, dict) and ev.get("ph") == "i"
                and ev.get("name") == "clock_offset"):
            continue
        b = (ev.get("args") or {}).get("bound_s")
        if isinstance(b, (int, float)):
            pid = ev.get("pid")
            # the estimate improves over the run, but events merged
            # EARLY were shifted under the then-current (cruder)
            # offset: the honest per-pid tolerance is the WORST bound
            # that was ever in effect, not the final tightest one
            bounds[pid] = max(b, bounds.get(pid, 0.0))
    return bounds


def validate_fleet(trace, skew_s=None) -> List[str]:
    """Fleet-merge causality checks on top of `validate` (run both).

    For every router ``dispatch`` instant targeting (replica R,
    trace_id T): if worker R recorded any ``queued``/``request`` span
    start for T, at least one must start at-or-after the dispatch
    minus the skew tolerance — time cannot flow backwards across the
    RPC hop by more than the measured clock uncertainty. A worker with
    NO spans for a dispatched trace_id is tolerated (SIGKILL truncates
    streams mid-run; the merged timeline stays valid, just shorter).
    `skew_s` None = use the trace's own measured bounds (plus a small
    floor for quantization), falling back to 50 ms when unmeasured.
    """
    errors: List[str] = []
    events = trace.get("traceEvents", [])
    if not isinstance(events, list):
        return errors
    bounds = measured_skew(trace)
    default_skew = max(bounds.values()) if bounds else 0.05
    dispatches = []          # (ts_us, replica, trace_id)
    starts = {}              # (pid, trace_id) -> [start_ts_us, ...]
    for ev in events:
        if not isinstance(ev, dict):
            continue
        args = ev.get("args") or {}
        if ev.get("ph") == "i" and ev.get("name") == "dispatch":
            if "replica" in args and "trace_id" in args:
                dispatches.append(
                    (ev.get("ts"), args["replica"], args["trace_id"])
                )
        elif ev.get("ph") in ("b", "X") and ev.get("name") in (
                "queued", "request"):
            tid = args.get("trace_id", ev.get("id"))
            if tid is not None:
                key = (ev.get("pid"), tid)
                starts.setdefault(key, []).append(ev.get("ts"))
    if not dispatches:
        return errors
    for ts, replica, trace_id in dispatches:
        got = starts.get((replica, trace_id))
        if not got:
            continue  # truncated worker stream: tolerated
        skew = skew_s if skew_s is not None else max(
            bounds.get(replica, default_skew), 0.001
        )
        tol_us = skew * 1e6
        if max(got) < ts - tol_us:
            errors.append(
                f"causality: dispatch of {trace_id!r} to replica "
                f"{replica} at {ts}us but every worker-side span "
                f"starts before it (latest {max(got)}us, "
                f"tolerance {tol_us:.0f}us) — merge offsets wrong?"
            )
    return errors


def chrome_from_stream(records) -> dict:
    """Assemble streamed telemetry records into a Chrome trace object.

    Lane spans arrive COMPLETE (the recorder streams at span end), so
    they export as ph "X" (ts + dur) — no B/E pairing to get wrong;
    async spans become adjacent b/e pairs keyed by trace_id; instants
    and lane-label metadata map 1:1. Non-trace kinds (flight, metrics,
    alert, telemetry_close) are skipped: they ride the same JSONL but
    belong to tools/check_slo.py.
    """
    events = []

    def us(t):
        return round(float(t) * 1e6, 3)

    def args_of(r):
        args = dict(r.get("attrs") or {})
        if r.get("trace_id") is not None:
            args["trace_id"] = r["trace_id"]
        return args

    for r in records:
        kind = r.get("kind")
        if kind == "meta":
            if r.get("meta") == "process_name":
                events.append({
                    "name": "process_name", "ph": "M",
                    "pid": r["pid"], "tid": 0,
                    "args": {"name": r["name"]},
                })
            else:
                events.append({
                    "name": "thread_name", "ph": "M",
                    "pid": r["pid"], "tid": r["tid"],
                    "args": {"name": r["name"]},
                })
        elif kind == "span":
            ev = {"name": r["name"], "ph": "X", "ts": us(r["t0"]),
                  "dur": round((r["t1"] - r["t0"]) * 1e6, 3),
                  "pid": r["pid"], "tid": r["tid"]}
            args = args_of(r)
            if args:
                ev["args"] = args
            events.append(ev)
        elif kind == "async":
            base = {"name": r["name"], "cat": "request",
                    "id": r["trace_id"], "pid": r["pid"], "tid": 0}
            b = dict(base, ph="b", ts=us(r["t0"]))
            args = args_of(r)
            if args:
                b["args"] = args
            events.append(b)
            events.append(dict(base, ph="e", ts=us(r["t1"])))
        elif kind == "instant":
            ev = {"name": r["name"], "ph": "i", "s": "t",
                  "ts": us(r["t"]), "pid": r["pid"],
                  "tid": r.get("tid", 0)}
            args = args_of(r)
            if args:
                ev["args"] = args
            events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def iter_stream_records(text: str):
    """Tail-tolerant telemetry JSONL loader -> (records, truncated,
    errors).

    THE parsing rule of the streaming format, shared with
    tools/check_slo.py: `truncated` is True when the LAST line failed
    to parse — the signature of a run killed mid-write, tolerated by
    design. An unparseable line anywhere ELSE lands in `errors`: the
    line-by-line format means a crash can only ever damage the tail.
    A file whose ONLY line is the truncated one yields no records and
    an error — that is a corrupt single-JSON artifact, not a stream.
    """
    errors = []
    records = []
    truncated = False
    lines = text.split("\n")
    # drop trailing empty strings from the final newline
    while lines and not lines[-1].strip():
        lines.pop()
    for i, ln in enumerate(lines):
        ln = ln.strip()
        if not ln:
            continue
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                truncated = True
            else:
                errors.append(f"line {i + 1}: unparseable JSONL (only "
                              "the final line may be crash-truncated)")
            continue
        if not isinstance(rec, dict) or "kind" not in rec:
            errors.append(f"line {i + 1}: not a kind-tagged object")
            continue
        records.append(rec)
    if truncated and not records:
        errors.append(
            "no parseable line at all — a truncated single-JSON dump, "
            "not a telemetry stream"
        )
    elif not records and not errors:
        errors.append("empty file — neither a trace dump nor a stream")
    return records, truncated, errors


def parse_stream_text(text: str):
    """Parse telemetry JSONL -> (chrome_trace, truncated_tail, errors)."""
    records, truncated, errors = iter_stream_records(text)
    return chrome_from_stream(records), truncated, errors


def summarize(trace) -> dict:
    """Counts for the CLI report: events by phase, spans by name."""
    events = trace.get("traceEvents", [])
    by_ph = Counter(ev.get("ph") for ev in events if isinstance(ev, dict))
    spans = Counter(
        ev.get("name") for ev in events
        if isinstance(ev, dict) and ev.get("ph") in ("B", "b", "X")
    )
    pids = sorted({
        ev.get("pid") for ev in events
        if isinstance(ev, dict) and "pid" in ev
    }, key=str)
    return {"events": len(events), "by_ph": dict(by_ph),
            "spans": dict(spans), "pids": pids}


def main(argv=None) -> int:
    args = sys.argv[1:] if argv is None else list(argv)
    if not args or args[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    fleet = False
    skew_s = None
    paths = []
    it = iter(args)
    for a in it:
        if a == "--fleet":
            fleet = True
        elif a == "--skew-s":
            try:
                skew_s = float(next(it))
            except (StopIteration, ValueError):
                print("--skew-s wants a number (seconds)")
                return 1
        else:
            paths.append(a)
    if not paths:
        print("no trace files given")
        return 1
    rc = 0
    for path in paths:
        try:
            with open(path) as f:
                text = f.read()
        except OSError as e:
            print(f"{path}: UNREADABLE — {e}")
            rc = 1
            continue
        # auto-detect: a Chrome dump is ONE JSON object; anything that
        # doesn't parse whole is treated as streamed JSONL
        trace = None
        note = ""
        if text.lstrip().startswith("{"):
            try:
                parsed = json.loads(text)
                # a one-line JSONL file also parses whole — only a
                # traceEvents object is actually the dump form
                if isinstance(parsed, dict) and "traceEvents" in parsed:
                    trace = parsed
            except json.JSONDecodeError:
                trace = None
        if trace is None:
            trace, truncated, errors = parse_stream_text(text)
            if truncated:
                note = " (crash-truncated tail line skipped)"
        else:
            errors = []
        errors += validate(trace)
        if fleet:
            errors += validate_fleet(trace, skew_s)
        dropped = 0
        sampling = None
        if isinstance(trace, dict):
            md = trace.get("metadata")
            if isinstance(md, dict):
                dropped = md.get("trace_events_dropped", 0) or 0
                if isinstance(md.get("sampling"), dict):
                    sampling = md["sampling"]
        s = summarize(trace)
        if errors:
            rc = 1
            print(f"{path}: INVALID ({len(errors)} error(s); "
                  f"{s['events']} events)")
            for e in errors[:20]:
                print(f"  - {e}")
            if len(errors) > 20:
                print(f"  ... and {len(errors) - 20} more")
        else:
            top = sorted(s["spans"].items(), key=lambda kv: -kv[1])[:8]
            spans = ", ".join(f"{n} x{c}" for n, c in top) or "none"
            print(f"{path}: OK — {s['events']} events, "
                  f"pids {s['pids']}, spans: {spans}{note}")
        if sampling:
            # informational, NOT a warning: suppressed spans are an
            # operator policy (head rate), not data loss — the tail
            # keep-rules promoted every anomalous trace regardless
            kept = sampling.get("kept_reasons") or {}
            reasons = ", ".join(
                f"{k}={v}" for k, v in sorted(kept.items())) or "none"
            print(f"{path}: INFO — sampled timeline (head rate "
                  f"{sampling.get('head_rate')}): "
                  f"{sampling.get('spans_suppressed', 0)} span(s) "
                  f"suppressed by policy, "
                  f"{sampling.get('traces_kept', 0)} trace(s) "
                  f"tail-kept ({reasons}); partial lanes here are "
                  f"sampling, not loss")
        if dropped:
            # a warning, not a verdict: the timeline is valid but has a
            # hole — whoever reads it should know before trusting gaps
            print(f"{path}: WARNING — {dropped} trace event(s) were "
                  f"dropped (bounded buffers, distinct from sampling "
                  f"suppression); the timeline is truncated, not "
                  f"corrupt")
    return rc


if __name__ == "__main__":
    sys.exit(main())
