"""Offline per-tenant QoS verdict over a streamed telemetry JSONL.

tools/check_slo.py answers "did the RUN meet its SLOs"; this tool
answers the multi-tenant question the QoS plane (serve/fairshare.py,
serve/slo.py TenantSLORegistry) exists for: did each TENANT meet its
SLOs, was service shared fairly, and did the isolation story hold —
did the hostile tenant's burn alert trip while the compliant tenants'
did not? Used two ways:

- as a library from tests: ``qos_report`` over parsed records (the
  tier-1 artifact test runs it over the checked-in qos bench
  telemetry);
- as a CLI over bench artifacts::

      python tools/check_qos.py --slo '{"ttft_p99_s": 0.5}' \\
          --hostile bulk --min-fairness 0.9 run.jsonl

  exit 0 = every verdict green, 1 = a tenant verdict or the fairness /
  isolation gate failed, 2 = input unreadable.

Verdict rules (each one an isolation claim):

- every NON-hostile tenant must meet every configured objective AND
  record zero alert trips — a compliant tenant paging during someone
  else's flood is precisely the failure weighted-fair scheduling
  exists to prevent;
- tenants named ``--hostile`` are exempt from the SLO verdict (their
  latency is the cost of their own flood, not a system failure); with
  ``--expect-hostile-trip`` their burn alert MUST have tripped, which
  pins that the per-tenant watchdogs actually attribute the burn to
  the tenant causing it;
- Jain's fairness index over per-tenant output tokens delivered
  INSIDE the contended window (completions that finished before the
  last recorded arrival) must be at least ``--min-fairness``
  (0 disables). The window bound matters: a run that drains to idle
  eventually delivers every tenant's totals whatever the scheduler
  did, so only tokens delivered while load was still arriving can
  show who got served during the fight — under weighted-fair
  scheduling backlogged tenants converge to equal service there,
  under FIFO the flooder eats the fleet.

Percentile math, status semantics, and record parsing are SHARED with
check_slo.py / serve/slo.py, so per-tenant and whole-run verdicts can
never disagree about what a p99 means. slo_exempt flights (brown-out
sheds) are excluded from tenant verdicts for the same anti-windup
reason the live watchdog never judged them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddp_practice_tpu.serve.fairshare import jains_index, tenant_name  # noqa: E402
from ddp_practice_tpu.serve.slo import SLOConfig  # noqa: E402
from tools.check_slo import load_events, slo_report  # noqa: E402


def _tenant_of(record: dict) -> str:
    return tenant_name(record.get("tenant"))


def qos_report(records: List[dict], config: SLOConfig, *,
               hostile: Sequence[str] = (),
               min_fairness: float = 0.0,
               expect_hostile_trip: bool = False) -> dict:
    """Per-tenant SLO reports + fairness + isolation verdict."""
    hostile_set = {tenant_name(h) for h in hostile}
    flights = [r for r in records if r.get("kind") == "flight"]
    tenants = sorted({_tenant_of(r) for r in flights})
    if not tenants:
        raise ValueError("no flight records — nothing to judge")
    # the contended-window bound for the fairness verdict (module doc)
    window_end = max((r["arrival"] for r in flights
                      if r.get("arrival") is not None), default=None)

    per_tenant: Dict[str, dict] = {}
    for t in tenants:
        # a tenant's view of the run: its own flights, plus only the
        # alert lines attributed to it (the registry labels every
        # per-tenant watchdog edge with tenant=...)
        mine = [
            r for r in records
            if (r.get("kind") == "flight" and _tenant_of(r) == t)
            or (r.get("kind") == "alert"
                and tenant_name(r.get("tenant")) == t)
            or (r.get("kind") == "instant"
                and r.get("name") in ("slo_alert", "slo_resolve")
                and tenant_name((r.get("attrs") or {}).get("tenant")) == t)
        ]
        rep = slo_report(mine, config)
        rep["hostile"] = t in hostile_set
        judged = [r for r in mine if r.get("kind") == "flight"
                  and not r.get("slo_exempt")]
        rep["output_tokens"] = sum(int(r.get("tokens") or 0)
                                   for r in judged)
        rep["window_tokens"] = sum(
            int(r.get("tokens") or 0) for r in judged
            if window_end is not None
            and r.get("finish") is not None
            and r["finish"] <= window_end)
        per_tenant[t] = rep

    service = [per_tenant[t]["window_tokens"] for t in tenants]
    fairness = jains_index(service)

    problems: List[str] = []
    for t in tenants:
        rep = per_tenant[t]
        if rep["hostile"]:
            continue
        bad = [n for n, o in rep["objectives"].items() if not o["met"]]
        if bad:
            problems.append(f"tenant {t}: violated {', '.join(bad)}")
        if rep["trips"]:
            problems.append(
                f"tenant {t}: {rep['trips']} alert trip(s) on a "
                "compliant tenant")
    if min_fairness > 0 and fairness < min_fairness:
        problems.append(
            f"fairness index {fairness:.4f} < {min_fairness}")
    if expect_hostile_trip:
        tripped = [t for t in hostile_set
                   if per_tenant.get(t, {}).get("trips")]
        if not tripped:
            problems.append(
                "no hostile tenant tripped its burn alert "
                f"(expected one of {sorted(hostile_set)})")

    return {
        "tenants": per_tenant,
        "fairness_index": fairness,
        "service_tokens": dict(zip(tenants, service)),
        "problems": problems,
        "ok": not problems,
    }


def render(path: str, report: dict, truncated: bool) -> str:
    lines = [f"{path}: {'OK' if report['ok'] else 'QOS VIOLATED'} — "
             f"{len(report['tenants'])} tenant(s), fairness index "
             f"{report['fairness_index']:.4f}"
             + (" (crash-truncated tail line skipped)" if truncated
                else "")]
    for t, rep in report["tenants"].items():
        tag = " [hostile]" if rep["hostile"] else ""
        lines.append(
            f"  {t}{tag}: {rep['flights']} flights, "
            f"{rep['output_tokens']} tokens out, "
            f"{rep['trips']} trip(s)")
        for name, o in rep["objectives"].items():
            verdict = "met" if o["met"] else (
                "violated (hostile, not judged)" if rep["hostile"]
                else "VIOLATED")
            lines.append(
                f"    {name:>12}: measured {o['measured']:.6g} vs "
                f"target {o['target']:.6g} — {verdict}")
    for p in report["problems"]:
        lines.append(f"  PROBLEM: {p}")
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "check_qos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--slo", required=True, metavar="JSON|PATH",
                   help="per-tenant SLO config: a JSON object literal "
                        "or a path (serve/slo.py SLOConfig keys); "
                        "applied to every tenant")
    p.add_argument("--hostile", action="append", default=[],
                   metavar="TENANT",
                   help="tenant exempt from the SLO verdict (its pain "
                        "is self-inflicted); repeatable")
    p.add_argument("--min-fairness", dest="min_fairness", type=float,
                   default=0.0, metavar="X",
                   help="fail if Jain's index over per-tenant output "
                        "tokens is below X (0 = no gate)")
    p.add_argument("--expect-hostile-trip", dest="expect_hostile_trip",
                   action="store_true",
                   help="fail unless at least one --hostile tenant's "
                        "burn alert tripped (isolation attribution)")
    p.add_argument("--json", action="store_true",
                   help="print the report(s) as one JSON object")
    p.add_argument("files", nargs="+", metavar="TELEMETRY_JSONL")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        config = SLOConfig.from_json(args.slo)
    except (ValueError, TypeError, json.JSONDecodeError) as e:
        print(f"bad --slo: {e}", file=sys.stderr)
        return 2
    rc = 0
    reports = {}
    for path in args.files:
        try:
            records, truncated = load_events(path)
            report = qos_report(
                records, config, hostile=args.hostile,
                min_fairness=args.min_fairness,
                expect_hostile_trip=args.expect_hostile_trip)
        except (OSError, ValueError) as e:
            print(f"{path}: UNREADABLE — {e}", file=sys.stderr)
            rc = 2
            continue
        reports[path] = report
        if not args.json:
            print(render(path, report, truncated))
        if not report["ok"] and rc == 0:
            rc = 1
    if args.json:
        print(json.dumps(reports))
    return rc


if __name__ == "__main__":
    sys.exit(main())
