"""Fleet health verdict over the federated /metrics + /healthz plane.

The cross-process fleet (serve/supervisor.py) rolls every worker's
telemetry into one registry (utils/telemetry.py ScrapeFederator); this
tool turns that rollup into an exit code a CI step or an operator's
probe can act on:

- as a CLI over a LIVE federated endpoint::

      python tools/check_fleet.py http://127.0.0.1:9100
      python tools/check_fleet.py --max-heartbeat-age 10 http://...

- or over a SNAPSHOT file — the federated /healthz JSON body saved to
  disk (the checked-in artifacts in tests/data/ pin both exit codes,
  the PR-5 test_tools_artifacts.py pattern)::

      python tools/check_fleet.py tests/data/fleet_healthz_ok.json

exit 0 = every worker healthy and fresh; 1 = the fleet has a problem
(a dead/stale worker, a FAILED slot whose restart budget is spent, or
an overall DEAD verdict); 2 = input unreadable/malformed — a broken
probe must be distinguishable from a broken fleet.

The verdict logic is a pure function (`fleet_verdict`) shared by the
CLI and the tests, judging exactly the fields the federator publishes:
per-worker ``status`` (healthy / degraded / stale / dead), supervisor
``state`` (a ``failed`` slot is an operator page even while its peers
serve), and ``heartbeat_age_s`` against the staleness budget.

Elastic fleets (serve/autoscaler.py) add two wrinkles this tool
understands: workers marked ``draining`` are an INTENTIONAL goodbye —
their stale heartbeats and dead probes are skipped, not paged — and an
``autoscaler`` block (current/min/max size, standby depth, last scale
event) is rendered and judged (a size outside [min, max] means the
control loop and the supervisor disagree about the world).

Multi-tenant fleets (serve/fairshare.py) add a third view: the
federated ``/tenants`` rollup (per-tenant request/token/cost counters,
pooled TTFT/TPOT percentiles, service shares and Jain's fairness
index). It is rendered per tenant, and ``--min-fairness X`` turns it
into a verdict — a fleet whose fairness index has collapsed below X is
paged even while every worker is individually healthy, because a
starved tenant is an outage for THAT tenant. Snapshot files may carry
the rollup under a ``"tenants"`` key next to ``"healthz"``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Tuple

OK, PROBLEM, UNREADABLE = 0, 1, 2


def _fetch(url: str, path: str, timeout_s: float = 3.0) -> str:
    import http.client
    from urllib.parse import urlparse

    u = urlparse(url)
    if u.scheme == "https":
        conn = http.client.HTTPSConnection(
            u.hostname, u.port or 443, timeout=timeout_s
        )
    else:
        conn = http.client.HTTPConnection(
            u.hostname, u.port or 80, timeout=timeout_s
        )
    conn.request("GET", path)
    body = conn.getresponse().read().decode("utf-8", "replace")
    conn.close()
    return body


def fetch_healthz(url: str, timeout_s: float = 3.0) -> dict:
    """GET <url>/healthz from a live federated TelemetryServer."""
    return json.loads(_fetch(url, "/healthz", timeout_s))


def fetch_flight(url: str, timeout_s: float = 3.0):
    """GET <url>/flight (the federated latency rollup); None when the
    endpoint is missing/unparseable — latency is a VIEW in the verdict
    output, never a reason to call the probe broken."""
    try:
        body = json.loads(_fetch(url, "/flight", timeout_s))
        return body if isinstance(body, dict) else None
    except Exception:
        return None


def fetch_tenants(url: str, timeout_s: float = 3.0):
    """GET <url>/tenants (the federated per-tenant QoS rollup); None
    when the endpoint is missing — a single-tenant fleet has no rollup
    and that is not a probe failure."""
    try:
        body = json.loads(_fetch(url, "/tenants", timeout_s))
        return body if isinstance(body, dict) and body.get("tenants") \
            else None
    except Exception:
        return None


def load_snapshot_doc(path: str):
    """One read of a snapshot file -> (healthz, flight|None,
    tenants|None). The file is either a bare federated /healthz body
    or a full-plane wrapper {"healthz": {...}, "metrics": "...",
    "flight": {...}, "tenants": {...}}."""
    with open(path) as f:
        data = json.load(f)
    flight = None
    tenants = None
    if isinstance(data, dict) and "healthz" in data:
        fl = data.get("flight")
        flight = fl if isinstance(fl, dict) else None
        tn = data.get("tenants")
        tenants = tn if isinstance(tn, dict) and tn.get("tenants") \
            else None
        data = data["healthz"]
    if not isinstance(data, dict) or "workers" not in data:
        raise ValueError("not a federated healthz body "
                         "(no 'workers' key)")
    return data, flight, tenants


def load_snapshot(path: str) -> dict:
    """A saved federated /healthz body (see load_snapshot_doc)."""
    return load_snapshot_doc(path)[0]


def fleet_verdict(healthz: dict,
                  max_heartbeat_age_s: float = 5.0
                  ) -> Tuple[bool, List[str]]:
    """(ok, problems): ok only when every worker is healthy, no slot's
    restart budget is spent, and no heartbeat is older than the
    budget."""
    problems: List[str] = []
    workers = healthz.get("workers", {})
    if not workers:
        problems.append("no workers in the fleet")
    overall = str(healthz.get("status", "")).upper()
    if overall == "DEAD":
        problems.append("overall verdict DEAD (no worker can serve)")
    for wid in sorted(workers):
        w = workers[wid]
        if w.get("draining"):
            # scale-down in progress: a draining worker going quiet is
            # the drain WORKING, not an incident
            continue
        status = str(w.get("status", "dead")).lower()
        if status != "healthy":
            problems.append(f"worker {wid}: status {status}")
        if str(w.get("state", "")).lower() == "failed":
            problems.append(
                f"worker {wid}: restart budget exhausted "
                f"(supervisor slot FAILED after "
                f"{w.get('restarts', '?')} restarts)"
            )
        hb = w.get("heartbeat_age_s")
        if hb is not None and hb > max_heartbeat_age_s:
            problems.append(
                f"worker {wid}: heartbeat stale "
                f"({hb:.2f}s > {max_heartbeat_age_s}s)"
            )
        kv = w.get("kv")
        if isinstance(kv, dict):
            used = kv.get("blocks_used", 0)
            total = kv.get("blocks_total", 0)
            if total and used > total:
                # more blocks in use than the pool holds: the summary
                # (or the allocator behind it) is lying — page, because
                # cache-aware routing scores against this very payload
                problems.append(
                    f"worker {wid}: cache accounting broken "
                    f"({used} blocks used of {total})"
                )
    asc = healthz.get("autoscaler")
    if isinstance(asc, dict):
        size = asc.get("size")
        lo, hi = asc.get("min"), asc.get("max")
        if size is not None and lo is not None and size < lo:
            problems.append(
                f"autoscaler: fleet size {size} below min {lo}"
            )
        if size is not None and hi is not None and size > hi:
            problems.append(
                f"autoscaler: fleet size {size} above max {hi}"
            )
    return (not problems, problems)


def tenant_problems(tenants, min_fairness: float) -> List[str]:
    """Verdict over the federated /tenants rollup. Only judged when
    ``--min-fairness`` asks for it: a fairness index below the floor
    pages, and so does asking for the judgment on a fleet that
    publishes no rollup (a fairness gate against nothing is a
    misconfigured probe, same logic as exit 2 for a bad file)."""
    if min_fairness <= 0:
        return []
    if not isinstance(tenants, dict) or not tenants.get("tenants"):
        return ["--min-fairness set but the fleet publishes no "
                "/tenants rollup (fair mode off?)"]
    fi = tenants.get("fairness_index")
    if fi is None:
        return ["/tenants rollup has no fairness_index"]
    if fi < min_fairness:
        service = tenants.get("service") or {}
        starved = min(service, key=service.get) if service else "?"
        return [f"fairness index {fi:.4f} < {min_fairness} "
                f"(most-starved tenant: {starved})"]
    return []


def _tenant_lines(tenants) -> List[str]:
    """The per-tenant rollup view (federated /tenants): cost counters,
    pooled latency percentiles, and each tenant's service share."""
    if not isinstance(tenants, dict) or not tenants.get("tenants"):
        return []
    out = [f"  tenants (fleet rollup, fairness index "
           f"{tenants.get('fairness_index', 0.0):.4f}):"]
    share = tenants.get("share") or {}
    for name, e in sorted((tenants.get("tenants") or {}).items()):
        reqs = e.get("requests") or {}
        ttft = e.get("ttft_s") or {}
        secs = e.get("seconds") or {}
        out.append(
            f"    {name:>12}: {sum(reqs.values()):5d} req"
            f"  {e.get('output_tokens', 0):7d} tok out"
            f"  {e.get('prompt_tokens', 0):7d} prompt"
            f"  share {share.get(name, 0.0) * 100:5.1f}%"
            f"  ttft p99 {ttft.get('p99', 0.0) * 1e3:8.2f} ms"
            f"  cost {sum(secs.values()):.3f}s"
        )
    return out


def _flight_lines(flight: dict) -> List[str]:
    """The rolled-up latency view (federated /flight): fleet TTFT/TPOT
    and phase percentiles over the pooled worker samples."""
    fleet = (flight or {}).get("fleet") or {}
    out: List[str] = []
    keys = [k for k in ("ttft_s", "tpot_s", "queue_s", "prefill_s",
                        "decode_s", "stall_s") if isinstance(
                            fleet.get(k), dict)]
    if not keys:
        return out
    out.append(f"  latency (fleet rollup over "
               f"{fleet.get('window', '?')} flights):")
    for k in keys:
        p = fleet[k]
        out.append(
            f"    {k:>10}: p50 {p.get('p50', 0) * 1e3:8.2f} ms"
            f"  p99 {p.get('p99', 0) * 1e3:8.2f} ms"
        )
    for name, ex in (fleet.get("exemplars") or {}).items():
        out.append(
            f"    exemplar {name}: trace_id {ex.get('trace_id')!r} "
            f"({ex.get('value', 0) * 1e3:.2f} ms on worker "
            f"{ex.get('worker', '?')})"
        )
    return out


def render(source: str, healthz: dict, ok: bool,
           problems: List[str], flight: dict = None,
           tenants: dict = None) -> str:
    lines = [f"{source}: fleet {healthz.get('status', '?')}"]
    for wid in sorted(healthz.get("workers", {})):
        w = healthz["workers"][wid]
        hb = w.get("heartbeat_age_s")
        lines.append(
            f"  worker {wid}: {w.get('status', '?'):>8}"
            f"  pid {str(w.get('pid', '-')):>7}"
            f"  state {str(w.get('state', '-')):>8}"
            f"  restarts {w.get('restarts', 0)}"
            f"  heartbeat "
            + (f"{hb:.2f}s" if hb is not None else "-")
            + ("  [draining]" if w.get("draining") else "")
        )
        kv = w.get("kv")
        if isinstance(kv, dict):
            # the heartbeat-carried cache summary the affinity router
            # scores against (serve/affinity.py): occupancy, hit rate,
            # and the digest's version/entry-count — its age IS the
            # heartbeat age (it rode the same frame)
            hit_rate = kv.get("prefix_hit_rate", 0.0)
            line = (
                f"    cache: blocks {kv.get('blocks_used', 0)}"
                f"/{kv.get('blocks_total', 0)}"
                f" ({kv.get('blocks_shared', 0)} shared)"
                f"  hit rate {hit_rate * 100:.1f}%"
            )
            dg = kv.get("digest")
            if isinstance(dg, dict):
                line += (
                    f"  digest v{dg.get('v', '?')}"
                    f" ({dg.get('n', 0)} prefixes"
                    + (f", age {hb:.2f}s" if hb is not None else "")
                    + ")"
                )
            lines.append(line)
    asc = healthz.get("autoscaler")
    if isinstance(asc, dict):
        lines.append(
            f"  autoscaler: size {asc.get('size', '?')} "
            f"(min {asc.get('min', '?')}, max {asc.get('max', '?')})"
            f"  standbys {asc.get('standby_ready', 0)}/"
            f"{asc.get('standby_target', 0)}"
            f"  draining {asc.get('draining') or []}"
            f"  events {asc.get('events_total', 0)}"
        )
        last = asc.get("last_event")
        if isinstance(last, dict):
            join = last.get("join_s")
            lines.append(
                f"    last event: {last.get('direction', '?')} "
                f"({last.get('trigger', '?')}) -> size "
                f"{last.get('size', '?')}"
                + (f", join {join:.3f}s" if join is not None else "")
            )
    lines.extend(_tenant_lines(tenants))
    lines.extend(_flight_lines(flight))
    if ok:
        lines.append(f"{source}: OK")
    else:
        for p in problems:
            lines.append(f"  PROBLEM: {p}")
        lines.append(f"{source}: FLEET UNHEALTHY")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "check_fleet",
        description="verdict over the federated fleet "
                    "/healthz (live URL or snapshot file)",
    )
    p.add_argument("targets", nargs="+",
                   help="http://host:port of the federated "
                        "TelemetryServer, or a JSON snapshot path")
    p.add_argument("--max-heartbeat-age", type=float, default=5.0,
                   metavar="S", dest="max_age",
                   help="heartbeats older than this are a failure "
                        "(default 5s)")
    p.add_argument("--min-fairness", type=float, default=0.0,
                   metavar="X", dest="min_fairness",
                   help="page when the federated /tenants rollup's "
                        "Jain's fairness index is below X "
                        "(0 = view only, no verdict)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report per target")
    args = p.parse_args(argv)
    rc = OK
    reports = {}
    for target in args.targets:
        flight = None
        tenants = None
        try:
            if target.startswith(("http://", "https://")):
                healthz = fetch_healthz(target)
                flight = fetch_flight(target)
                tenants = fetch_tenants(target)
            else:
                healthz, flight, tenants = load_snapshot_doc(target)
        except Exception as e:
            if args.json:
                reports[target] = {"error": str(e)}
            else:
                print(f"{target}: UNREADABLE ({e})")
            rc = max(rc, UNREADABLE)
            continue
        ok, problems = fleet_verdict(healthz, args.max_age)
        tp = tenant_problems(tenants, args.min_fairness)
        if tp:
            problems = problems + tp
            ok = False
        reports[target] = {
            "ok": ok, "status": healthz.get("status"),
            "problems": problems,
            "workers": {
                wid: w.get("status")
                for wid, w in healthz.get("workers", {}).items()
            },
        }
        if isinstance(healthz.get("autoscaler"), dict):
            reports[target]["autoscaler"] = healthz["autoscaler"]
        if flight is not None:
            reports[target]["flight"] = flight.get("fleet", flight)
        if tenants is not None:
            reports[target]["tenants"] = {
                "fairness_index": tenants.get("fairness_index"),
                "share": tenants.get("share"),
                "names": sorted((tenants.get("tenants") or {})),
            }
        if not args.json:
            print(render(target, healthz, ok, problems, flight,
                         tenants))
        if not ok:
            rc = max(rc, PROBLEM)
    if args.json:
        print(json.dumps(reports))
    return rc


if __name__ == "__main__":
    sys.exit(main())
