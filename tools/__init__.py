# repo tooling (CI validators, artifact checkers) — importable from tests
