"""Bench-regression ledger: gate BENCH_serve.json against a baseline.

Every PR re-measures the serving benches (BENCH_serve.json), but until
now nothing COMPARED runs — a fleet-overhead ratio could quietly creep
from 1.10x to 1.4x across three PRs and every individual report would
still look plausible. This tool is the gate: a checked-in baseline
(tests/data/bench_baseline.json, refreshed deliberately when a number
moves for a REASON) plus per-key tolerances, and an exit code CI can
act on:

    python tools/check_bench.py BENCH_serve.json
    python tools/check_bench.py --baseline old.json --gates g.json new.json

exit 0 = every gated key within tolerance of the baseline; 1 = at
least one regression (or a gated key vanished from the current file —
a dropped measurement is a silent regression too); 2 = input
unreadable/malformed — a broken comparison must be distinguishable
from a broken bench.

Gates are dotted paths into the bench JSON with a direction and a
tolerance::

    {"fleet_x2_overhead_8rps.latency_ratio_p50":
        {"direction": "lower", "tol": 0.15}}

``lower`` = lower is better (latency ratios): current must be <=
baseline * (1 + tol). ``higher`` = higher is better (goodput ratios):
current >= baseline * (1 - tol). A baseline value of 0 degenerates to
an absolute bound of tol (the zero-lost invariant: baseline 0 lost,
tol 0 -> current must be 0). A gated key measured in CURRENT but
absent from the BASELINE is "new" — it PASSES with a note (a new
bench entry has no history yet; landing it must not require
hand-editing old baselines) and becomes gated when the baseline is
refreshed. A key absent from BOTH sides is skipped; one that was in
the baseline but vanished from current is a MISSING failure (that is
how a regression hides).

The default gate set covers the serving headlines this repo's
acceptance criteria actually pinned: the RPC-seam and trace-plane
overhead ratios, chaos goodput, and the zero-lost invariant.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

OK, REGRESSION, UNREADABLE = 0, 1, 2

# lower = smaller is better (overhead/latency ratios); higher = bigger
# is better (goodput/throughput ratios). tol is the allowed relative
# drift vs the baseline.
DEFAULT_GATES: Dict[str, dict] = {
    # the RPC seam's bill (PR 7 gate: p50 <= 1.10x) may drift, not creep
    "fleet_x2_overhead_8rps.latency_ratio_p50":
        {"direction": "lower", "tol": 0.15},
    "fleet_x2_overhead_8rps.goodput_ratio":
        {"direction": "higher", "tol": 0.15},
    # real-SIGKILL chaos: goodput under faults, and NOTHING lost — the
    # zero-lost invariant is absolute (tol 0 on a baseline of 0)
    "fleet_x2_sigkill_100rps.goodput_ratio":
        {"direction": "higher", "tol": 0.20},
    "fleet_x2_sigkill_100rps.fleet.lost":
        {"direction": "lower", "tol": 0.0},
    # observability planes must stay ~free (their acceptance gates)
    "tracing_overhead_100rps.mean_ratio":
        {"direction": "lower", "tol": 0.05},
    "telemetry_plane_overhead_100rps.mean_ratio":
        {"direction": "lower", "tol": 0.05},
    "fleet_trace_overhead_8rps.latency_ratio_p50":
        {"direction": "lower", "tol": 0.05},
    # the prefix cache's reason to exist
    "prefix_cache_100rps.prefix_vs_paged":
        {"direction": "higher", "tol": 0.20},
    # streaming delivery (ISSUE 10): per-burst chunks must be ~free vs
    # end-of-request delivery (acceptance gate: mean <= 1.05x), and the
    # exactly-once contract under real SIGKILL is ABSOLUTE — the
    # consumer-side recount of duplicated/missing tokens is gated at a
    # baseline of 0 with tol 0 (one duplicate is a broken contract, not
    # drift); resume-gap/inter-token p99s may drift, not creep
    "streaming_overhead_8rps.latency_ratio_mean":
        {"direction": "lower", "tol": 0.10},
    "fleet_x2_stream_sigkill_100rps.chunk_dupes":
        {"direction": "lower", "tol": 0.0},
    "fleet_x2_stream_sigkill_100rps.chunk_gaps":
        {"direction": "lower", "tol": 0.0},
    "fleet_x2_stream_sigkill_100rps.lost":
        {"direction": "lower", "tol": 0.0},
    "fleet_x2_stream_sigkill_100rps.resume_gap_p99_s":
        {"direction": "lower", "tol": 0.50},
    "fleet_x2_stream_sigkill_100rps.inter_token_p99_s":
        {"direction": "lower", "tol": 0.50},
    # sampled trace plane (ISSUE 11): a 1% head rate must stay ~free
    # (acceptance: mean <= 1.02x vs tracing off) and must actually
    # shed spans — the reduction vs full tracing is gated near its
    # >= 0.95 acceptance floor, drift-tolerant but not collapse-blind
    "trace_sampling_100rps.mean_ratio":
        {"direction": "lower", "tol": 0.05},
    "trace_sampling_100rps.span_reduction":
        {"direction": "higher", "tol": 0.04},
    # live OTLP push (ISSUE 12): the background pusher must never tax
    # the serve loop (acceptance: mean <= 1.02x vs file-only export),
    # and the adaptive head-rate controller must actually land kept-sps
    # within ±20% of its budget — that one is a CONTRACT, not a drift
    # band, so the bench reports within_budget as 0/1 and the gate is
    # absolute (baseline 1, tol 0: a single miss is a regression)
    "otlp_push_overhead_100rps.mean_ratio":
        {"direction": "lower", "tol": 0.05},
    "adaptive_sampling_100rps.within_budget":
        {"direction": "higher", "tol": 0.0},
    # speculative decoding (ISSUE 13): the single-stream TPOT win on
    # the lookup-friendly trace must hold (acceptance: ratio < 1.0x;
    # baseline ~0.74x so the drift band stays well under 1.0), the
    # accept rate explains the ratio and may drift but not collapse,
    # and greedy token-identity is a CONTRACT — one divergent stream
    # breaks the exactness claim, so baseline 1.0 is gated at tol 0
    "spec_decode_8rps.tpot_ratio":
        {"direction": "lower", "tol": 0.15},
    "spec_decode_8rps.accept_rate":
        {"direction": "higher", "tol": 0.25},
    "spec_decode_8rps.token_identity":
        {"direction": "higher", "tol": 0.0},
    # elastic fleet (ISSUE 14): the autoscaled arm must keep earning
    # its goodput-per-worker-second edge over the fixed fleet at equal
    # SLO (drift-tolerant), while the control-loop CONTRACTS are 0/1
    # absolutes — react within one evaluation window of the 4x step,
    # never thrash past the hold-window bound, lose nothing across
    # either arm — and a warm standby promotion must stay a fraction
    # of the ~15s cold spawn (absolute seconds bound, baseline-free)
    "autoscale_burst_100rps.goodput_per_worker_ratio":
        {"direction": "higher", "tol": 0.30},
    "autoscale_burst_100rps.lost":
        {"direction": "lower", "tol": 0.0},
    "autoscale_burst_100rps.reaction_within_window":
        {"direction": "higher", "tol": 0.0},
    "autoscale_burst_100rps.oscillation_ok":
        {"direction": "higher", "tol": 0.0},
    "autoscale_burst_100rps.promote_join_s":
        {"direction": "lower", "tol": 4.0},
    # cache-aware routing (ISSUE 15): affinity must keep beating
    # least-loaded on the fleet prefix-hit-token rate at the same
    # undersized pool (drift-tolerant — the contrast, not its exact
    # size, is the claim) without taxing goodput; zero-lost and greedy
    # token identity are CONTRACTS (routing changes WHERE a request
    # runs, never WHAT it produces), gated absolute
    "cache_routing_100rps.hit_rate_ratio":
        {"direction": "higher", "tol": 0.06},
    "cache_routing_100rps.goodput_ratio":
        {"direction": "higher", "tol": 0.06},
    "cache_routing_100rps.lost":
        {"direction": "lower", "tol": 0.0},
    "cache_routing_100rps.token_identity":
        {"direction": "higher", "tol": 0.0},
    # the wire surface (ISSUE 16): greedy token identity through real
    # sockets vs in-process Router.stream is a CONTRACT (baseline 1.0,
    # tol 0 — one diverged stream breaks the front door's whole
    # claim); chunked prefill must keep its TTFT-p99 edge on the mixed
    # long/short trace (acceptance: ratio <= 0.85x vs unchunked —
    # drift-tolerant, the CONTRAST is the claim); wire goodput must
    # track the in-process arm; zero lost streams under a mid-stream
    # worker SIGKILL and zero new decode compiles under mixed
    # greedy+sampled churn are absolutes
    "frontdoor_100rps.token_identity":
        {"direction": "higher", "tol": 0.0},
    "frontdoor_100rps.ttft_p99_ratio_chunked":
        {"direction": "lower", "tol": 0.15},
    "frontdoor_100rps.goodput_ratio":
        {"direction": "higher", "tol": 0.10},
    "frontdoor_100rps.sigkill_lost":
        {"direction": "lower", "tol": 0.0},
    "frontdoor_100rps.sampling_new_compiles":
        {"direction": "lower", "tol": 0.0},
    # tenant QoS plane (ISSUE 19): weighted-fair scheduling must keep
    # the compliant tenant's TTFT p99 well under FIFO's during a
    # hostile flood — the raw ratio sits near 0.03x and jitters 2x
    # run-to-run, so the gated form is the 0/1 verdict against the
    # <= 0.7x acceptance bound (isolation_ok), not the ratio itself.
    # Jain's index over the contended window must stay near its
    # >= 0.9 floor, and the rest are absolute CONTRACTS: fair
    # scheduling reorders WHO decodes next but never WHAT a greedy
    # request produces (token_identity 1.0 vs the FIFO arm, tol 0),
    # nothing lost, the hostile tenant's per-tenant burn alert trips
    # while the compliant tenant's stays silent, and the SIGKILL leg
    # keeps all of it (offline check_qos verdict + merged fleet trace
    # both green)
    "qos_mixed_tenants_100rps.isolation_ok":
        {"direction": "higher", "tol": 0.0},
    "qos_mixed_tenants_100rps.fairness_index":
        {"direction": "higher", "tol": 0.08},
    "qos_mixed_tenants_100rps.token_identity":
        {"direction": "higher", "tol": 0.0},
    "qos_mixed_tenants_100rps.lost":
        {"direction": "lower", "tol": 0.0},
    "qos_mixed_tenants_100rps.hostile_alert_tripped":
        {"direction": "higher", "tol": 0.0},
    "qos_mixed_tenants_100rps.compliant_clean":
        {"direction": "higher", "tol": 0.0},
    "qos_mixed_tenants_100rps.sigkill_lost":
        {"direction": "lower", "tol": 0.0},
    "qos_mixed_tenants_100rps.sigkill.token_identity":
        {"direction": "higher", "tol": 0.0},
    "qos_mixed_tenants_100rps.sigkill.check_qos_ok":
        {"direction": "higher", "tol": 0.0},
    "qos_mixed_tenants_100rps.sigkill.trace_ok":
        {"direction": "higher", "tol": 0.0},
}


def dig(obj, dotted: str):
    """Resolve "a.b.c" into nested dicts; None when any hop misses."""
    cur = obj
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def judge_key(key: str, gate: dict, current, baseline) -> dict:
    """One gated key's verdict row. `status`: ok / regression /
    skipped (no baseline history) / missing (vanished from current)."""
    direction = gate.get("direction", "lower")
    tol = float(gate.get("tol", 0.1))
    row = {"key": key, "direction": direction, "tol": tol,
           "baseline": baseline, "current": current}
    if baseline is None or not isinstance(baseline, (int, float)):
        if isinstance(current, (int, float)):
            # measured now, no history: a NEW entry passes with a note
            # instead of demanding a hand-edited baseline to land
            row["status"] = "new"
            row["note"] = ("new measurement, no baseline history — "
                           "passes; gated once the baseline refreshes")
        else:
            row["status"] = "skipped"
            row["note"] = "no baseline value — ungated until refreshed"
        return row
    if current is None or not isinstance(current, (int, float)):
        # the measurement DISAPPEARED: that is how a regression hides
        row["status"] = "missing"
        return row
    if direction == "lower":
        limit = baseline * (1.0 + tol) if baseline else tol
        row["limit"] = limit
        row["status"] = "ok" if current <= limit else "regression"
    elif direction == "higher":
        limit = baseline * (1.0 - tol)
        row["limit"] = limit
        row["status"] = "ok" if current >= limit else "regression"
    else:
        row["status"] = "regression"
        row["note"] = f"unknown direction {direction!r}"
    return row


def bench_verdict(current: dict, baseline: dict,
                  gates: Optional[Dict[str, dict]] = None
                  ) -> Tuple[bool, List[dict]]:
    """(ok, rows) over every gated key — the pure function the CLI and
    the artifact tests share."""
    rows = [
        judge_key(key, gate, dig(current, key), dig(baseline, key))
        for key, gate in sorted((gates or DEFAULT_GATES).items())
    ]
    ok = all(r["status"] in ("ok", "skipped", "new") for r in rows)
    return ok, rows


def _load(path_or_json: str) -> dict:
    text = path_or_json
    if not text.lstrip().startswith("{"):
        with open(text) as f:
            text = f.read()
    data = json.loads(text)
    if not isinstance(data, dict):
        raise ValueError("bench file must be a JSON object")
    return data


def render(source: str, ok: bool, rows: List[dict]) -> str:
    lines = []
    for r in rows:
        st = r["status"]
        mark = {"ok": "ok", "skipped": "--", "new": "NEW",
                "missing": "MISSING",
                "regression": "REGRESSION"}[st]
        cur = (f"{r['current']:.4g}"
               if isinstance(r["current"], (int, float)) else "-")
        base = (f"{r['baseline']:.4g}"
                if isinstance(r["baseline"], (int, float)) else "-")
        lim = (f" (limit {r['limit']:.4g})" if "limit" in r else "")
        lines.append(
            f"  {mark:>10}  {r['key']}: {cur} vs baseline {base}"
            f" [{r['direction']} ±{r['tol']:.0%}]{lim}"
        )
    lines.append(f"{source}: " + ("BENCH OK" if ok else "BENCH REGRESSION"))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "check_bench",
        description="gate a BENCH_serve.json against a baseline's "
                    "gated keys (latency/goodput ratios, per-key "
                    "tolerance)",
    )
    p.add_argument("current", help="bench JSON path (or literal)")
    p.add_argument("--baseline", default="tests/data/bench_baseline.json",
                   help="baseline bench JSON (default: the checked-in "
                        "ledger)")
    p.add_argument("--gates", default=None, metavar="JSON|PATH",
                   help="gate map override: dotted key -> "
                        '{"direction": "lower"|"higher", "tol": f}')
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    try:
        current = _load(args.current)
        baseline = _load(args.baseline)
        gates = _load(args.gates) if args.gates else None
        if gates is not None:
            for k, g in gates.items():
                if not isinstance(g, dict):
                    raise ValueError(f"gate {k!r} must be an object")
    except (OSError, ValueError) as e:
        print(f"UNREADABLE — {e}", file=sys.stderr)
        return UNREADABLE
    ok, rows = bench_verdict(current, baseline, gates)
    if args.json:
        print(json.dumps({"ok": ok, "rows": rows}))
    else:
        print(render(args.current, ok, rows))
    return OK if ok else REGRESSION


if __name__ == "__main__":
    sys.exit(main())
