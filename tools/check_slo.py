"""Offline SLO report/verdict over a streamed telemetry JSONL.

The live watchdog (serve/slo.py) answers "are we burning budget RIGHT
NOW"; this tool answers the post-hoc question over a whole run's
telemetry file (utils/telemetry.py TelemetryExporter): did the run meet
its SLOs, and what did the alerting actually do? Used two ways:

- as a library from tests: ``load_events`` + ``slo_report`` (the
  tier-1 artifact test runs it over the checked-in bench telemetry);
- as a CLI over bench artifacts::

      python tools/check_slo.py --slo '{"ttft_p99_s": 0.5}' run.jsonl
      python tools/check_slo.py --slo slo.json *.jsonl

  exit 0 = every objective met, 1 = at least one violated, 2 = input
  unreadable. The report prints measured vs target per objective plus
  the alert trip/resolve timeline the run recorded.

Config, status semantics (OK_STATUSES), and percentile math are SHARED
with the live plane (serve/slo.py SLOConfig,
utils/metrics.percentile_summary), so offline verdicts and online
alerts can never disagree about what a target or a p99 means. Input is
the line-by-line telemetry stream; a crash-truncated final line is
tolerated (that is the streaming format's whole point).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

# runnable as `python tools/check_slo.py` from the repo root: the
# package is imported from the working tree, not an installed dist
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddp_practice_tpu.serve.slo import OK_STATUSES, SLOConfig  # noqa: E402
from ddp_practice_tpu.utils.metrics import percentile_summary  # noqa: E402
from tools.check_traces import iter_stream_records  # noqa: E402


def load_events(path: str) -> Tuple[List[dict], bool]:
    """Parse a telemetry JSONL -> (records, truncated_tail).

    Shares the tail-tolerant parsing rule with tools/check_traces.py
    (iter_stream_records): only the FINAL line may fail to parse
    (SIGKILL mid-write); garbage anywhere else raises ValueError — it
    means the writer is broken, not that the run died.
    """
    with open(path) as f:
        text = f.read()
    records, truncated, errors = iter_stream_records(text)
    if errors:
        raise ValueError(f"{path}: {errors[0]}")
    return records, truncated


def slo_report(records: List[dict], config: SLOConfig) -> dict:
    """Evaluate each configured objective over the run's flight records.

    Per objective: the measured value, the target, and met/violated.
    A latency objective is met when its p99 over the whole run is at or
    under the target (the offline equivalent of "burn stayed <= 1");
    rate objectives compare the run's bad fraction to its budget.
    Alert lines (kind="alert") and slo_alert/slo_resolve instants are
    surfaced as the timeline, so a verdict can be cross-checked against
    what the live watchdog actually fired.
    """
    all_flights = [r for r in records if r.get("kind") == "flight"]
    # slo_exempt flights are the router's OWN brown-out sheds — the
    # live watchdog deliberately never judged them (anti-windup), so
    # the offline verdict must not either, or the two would disagree
    # about the same run
    flights = [r for r in all_flights if not r.get("slo_exempt")]
    ttft = [r["ttft"] for r in flights if r.get("ttft") is not None]
    tpot = [r["tpot"] for r in flights if r.get("tpot") is not None]
    statuses = [r.get("status", "") for r in flights]
    n = len(flights)

    objectives: dict = {}

    def add(name, measured, target, met, **extra):
        objectives[name] = {
            "measured": measured, "target": target,
            "met": bool(met), **extra,
        }

    if config.ttft_p99_s is not None:
        p99 = percentile_summary(ttft, (99,))["p99"]
        add("ttft_p99", p99, config.ttft_p99_s,
            bool(ttft) and p99 <= config.ttft_p99_s, samples=len(ttft))
    if config.tpot_p99_s is not None:
        p99 = percentile_summary(tpot, (99,))["p99"]
        add("tpot_p99", p99, config.tpot_p99_s,
            bool(tpot) and p99 <= config.tpot_p99_s, samples=len(tpot))
    if config.error_rate is not None:
        bad = sum(s == "error" for s in statuses)
        rate = bad / n if n else 0.0
        add("error_rate", rate, config.error_rate,
            n > 0 and rate <= config.error_rate, bad=bad, total=n)
    if config.availability is not None:
        ok = sum(s in OK_STATUSES for s in statuses)
        avail = ok / n if n else 0.0
        add("availability", avail, config.availability,
            n > 0 and avail >= config.availability, ok=ok, total=n)
    if not objectives:
        raise ValueError("SLO config enables no objective")

    alerts = [
        {"t": r.get("t"), "event": r["event"],
         "objective": r.get("objective")}
        for r in records if r.get("kind") == "alert"
    ]
    if not alerts:
        # no watchdog telemetry handle on this run: the same edges may
        # still be present as streamed tracer instants — use those
        # (never both, or every edge would count twice)
        for r in records:
            if r.get("kind") == "instant" and r.get("name") in (
                    "slo_alert", "slo_resolve"):
                alerts.append({
                    "t": r.get("t"),
                    "event": ("trip" if r["name"] == "slo_alert"
                              else "resolve"),
                    "objective": (r.get("attrs") or {}).get("objective"),
                })
    alerts.sort(key=lambda a: (a["t"] is None, a["t"]))

    return {
        "flights": n,
        "slo_exempt": len(all_flights) - n,
        "objectives": objectives,
        "ok": all(o["met"] for o in objectives.values()),
        "alerts": alerts,
        "trips": sum(a["event"] == "trip" for a in alerts),
    }


def render(path: str, report: dict, truncated: bool) -> str:
    lines = [f"{path}: {'OK' if report['ok'] else 'SLO VIOLATED'} — "
             f"{report['flights']} flight records"
             + (f" (+{report['slo_exempt']} slo-exempt brown-out sheds,"
                " not judged)" if report["slo_exempt"] else "")
             + (" (crash-truncated tail line skipped)" if truncated
                else "")]
    for name, o in report["objectives"].items():
        verdict = "met" if o["met"] else "VIOLATED"
        lines.append(
            f"  {name:>12}: measured {o['measured']:.6g} vs "
            f"target {o['target']:.6g} — {verdict}"
        )
    if report["alerts"]:
        lines.append(f"  alerts: {report['trips']} trip(s)")
        for a in report["alerts"]:
            t = f"{a['t']:.3f}" if a["t"] is not None else "?"
            lines.append(f"    t={t} {a['event']} {a['objective']}")
    else:
        lines.append("  alerts: none recorded")
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "check_slo", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--slo", required=True, metavar="JSON|PATH",
                   help="SLO config: a JSON object literal or a path "
                        "to a JSON file (serve/slo.py SLOConfig keys)")
    p.add_argument("--json", action="store_true",
                   help="print the report(s) as one JSON object")
    p.add_argument("files", nargs="+", metavar="TELEMETRY_JSONL")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        config = SLOConfig.from_json(args.slo)
    except (ValueError, TypeError, json.JSONDecodeError) as e:
        print(f"bad --slo: {e}", file=sys.stderr)
        return 2
    rc = 0
    reports = {}
    for path in args.files:
        try:
            records, truncated = load_events(path)
            report = slo_report(records, config)
        except (OSError, ValueError) as e:
            print(f"{path}: UNREADABLE — {e}", file=sys.stderr)
            rc = 2
            continue
        reports[path] = report
        if not args.json:
            print(render(path, report, truncated))
        if not report["ok"] and rc == 0:
            rc = 1
    if args.json:
        print(json.dumps(reports))
    return rc


if __name__ == "__main__":
    sys.exit(main())
