"""Block sweep for the restructured kernels: total fwd+bwd device time at
lm_base shapes, scored on USEFUL throughput (fixed useful causal FLOPs /
device ms) — finer blocks waste fewer masked FLOPs but pay more per-cell
overhead."""
import shutil
import sys
import tempfile

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, "/root/repo")
from ddp_practice_tpu.utils.xprof import op_summary

K = 24


def device_ms(fn, args):
    @jax.jit
    def run(q, k, v):
        def body(c, _):
            return fn(c, k, v), ()
        o, _ = lax.scan(body, q, None, length=K)
        return jnp.float32(o.astype(jnp.float32).sum())

    float(run(*args))
    tmp = tempfile.mkdtemp(prefix="xp_blk_")
    with jax.profiler.trace(tmp):
        float(run(*args))
    s = op_summary(tmp)
    shutil.rmtree(tmp, ignore_errors=True)
    return s["total_ps"] / 1e9 / K


def main():
    from ddp_practice_tpu.ops.flash_attention import flash_attention_with_lse

    bh, s, d = 96, 2048, 64
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (bh, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (bh, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (bh, s, d), jnp.bfloat16)

    useful = bh * 9 * 2.0 * s * s * d * 0.5  # 2 fwd + 7 bwd dots, causal

    for bq, bk in [(512, 1024), (512, 512), (256, 512), (1024, 512),
                   (256, 256), (1024, 1024), (128, 512), (512, 256)]:
        def fwdbwd(q, k, v, bq=bq, bk=bk):
            f = lambda q, k, v: flash_attention_with_lse(
                q, k, v, causal=True, block_q=bq, block_k=bk)[0].sum()
            dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
            return lax.clamp(-1.0, (dq + dk + dv).astype(jnp.float32),
                             1.0).astype(q.dtype)

        ms = device_ms(fwdbwd, (q, k, v))
        tf = useful / (ms / 1e3) / 1e12
        print(f"blocks ({bq:4d},{bk:4d}): {ms:7.3f} ms  useful {tf:6.1f}"
              f" TF/s ({100 * tf / 197:.1f}% of peak)")


if __name__ == "__main__":
    main()
