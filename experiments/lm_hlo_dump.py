"""Dump the compiled HLO of the lm_base train step and print the
definitions of the profiler's hot non-matmul ops, so each ms in the
profile maps to a source construct."""
import re
import sys

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, "/root/repo")


def main():
    from ddp_practice_tpu.config import MeshConfig, PrecisionPolicy, TrainConfig
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.parallel.mesh import (
        batch_sharding, build_mesh, replicated, shard_state)
    from ddp_practice_tpu.parallel.ring import set_current_mesh
    from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import _lm_train_step_fn

    seq_len, vocab, bsz, K = 2048, 32768, 8, 8
    mesh = build_mesh(MeshConfig(data=-1))
    set_current_mesh(mesh)
    policy = PrecisionPolicy.from_name("bf16")
    model = create_model("lm_base", policy=policy, vocab_size=vocab,
                         max_len=seq_len, attn_impl="flash")
    tcfg = TrainConfig(model="lm_base", optimizer="adamw", learning_rate=3e-4)
    tx = make_optimizer(tcfg)
    sample = jnp.zeros((bsz, seq_len), jnp.int32)
    abstract = jax.eval_shape(
        lambda r: create_state(model, tx, rng=r, sample_input=sample),
        jax.random.PRNGKey(0))
    shardings = shard_state(abstract, mesh, param_sharding_rules("lm_base"))

    step_fn = _lm_train_step_fn(model, tx, with_accuracy=False)
    bsh = batch_sharding(mesh)
    rep = replicated(mesh)
    base_key = jax.random.PRNGKey(1)

    def chunk(state):
        def body(st, key):
            tokens = jax.random.randint(
                key, (bsz, seq_len + 1), 0, vocab, dtype=jnp.int32)
            batch = {"tokens": lax.with_sharding_constraint(tokens, bsh)}
            return step_fn(st, batch)
        keys = jax.random.split(jax.random.fold_in(base_key, state.step), K)
        state, ms = lax.scan(body, state, keys)
        return state, jax.tree.map(lambda v: v[-1], ms)

    jchunk = jax.jit(chunk, donate_argnums=0, in_shardings=(shardings,),
                     out_shardings=(shardings, rep))
    compiled = jchunk.lower(abstract).compile()
    txt = compiled.as_text()
    with open("/tmp/lm_hlo.txt", "w") as f:
        f.write(txt)
    print(f"HLO dumped: {len(txt)} chars -> /tmp/lm_hlo.txt")

    targets = sys.argv[1:] or [
        "iota_reduce_fusion.2 ", "fusion.2355 ", "fusion.2352 ",
        "multiply_add_fusion.658 ", "fusion.2345 ", "copy.1428 ",
        "multiply_add_fusion.654 ", "multiply_reduce_fusion.125 ",
        "fusion.2351 ",
    ]
    for t in targets:
        pat = "%" + t.strip() + " "
        for line in txt.splitlines():
            if pat in line and "= " in line.split(pat)[0][-3:] or \
               line.strip().startswith(pat.strip() + " ="):
                print("----", t)
                print(line.strip()[:600])
                break


if __name__ == "__main__":
    main()
