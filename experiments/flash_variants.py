"""Flash-attention kernel variant shootout (round 4, VERDICT item 1).

Times fwd and fwd+bwd of candidate restructurings of ops/flash_attention.py
on the real chip at lm_base shapes (head_dim 64, seq 2048, causal) and
reports executed-dot TFLOP/s vs the chip's bf16 peak (hardware utilization
of the MXU, counting the dots each kernel actually runs — including bwd
recompute — over the causally visible blocks).

Variants:
  v1_fp32     — round-3 kernel: all operands upcast to fp32 before the dots.
  v2_bf16     — FlashAttention-2 staging: dots consume bf16 operands with
                fp32 accumulation (preferred_element_type); p / ds are cast
                to bf16 before their MXU consumers; softmax state stays fp32.
  v3_sumfold  — v2 + the softmax row-sum folded into the p@v matmul via a
                ones-augmented V (the d=64 output leaves half the MXU lanes
                idle anyway, so the extra column is free) — removes one VPU
                reduction pass per block.
  v4_2head    — v2 + two heads per grid cell (python-unrolled) to amortize
                per-cell overhead; contraction width is still head_dim so
                MXU utilization per dot is unchanged — this measures whether
                cell overhead, not array packing, is the limiter.

Timing: K-chained scan, fenced by scalar readback, slope between two chain
lengths (axon tunnel: block_until_ready does not fence; per-call overhead
~100 ms — see tpu-env-gotchas).
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _causal_mask(s, qi, kj, block_q, block_k, offset):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1)
    return jnp.where(q_pos + offset >= k_pos, s, _NEG_INF)


# ------------------------------------------------------------------ #
# v2: bf16-staged fwd kernel
# ------------------------------------------------------------------ #

def _fwd_v2(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
            *, sm_scale, block_q, block_k, causal, seq_q, seq_k):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = seq_k - seq_q if causal else 0

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    @pl.when(visible)
    def _compute():
        q = q_ref[:]                       # bf16
        k = k_ref[:]
        v = v_ref[:]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = (l_prev * corr + jnp.sum(p, axis=-1))[:, None]
        acc_scr[:] = acc_scr[:] * corr[:, None] + jnp.dot(
            p.astype(jnp.bfloat16), v, preferred_element_type=jnp.float32
        )
        m_scr[:] = m_new[:, None]

    @pl.when(kj == n_k - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[:] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[:] = (m_scr[:, 0] + jnp.log(l_safe))[:, None]


# ------------------------------------------------------------------ #
# v3: v2 + row-sum folded into the p@v matmul (ones-augmented V)
# ------------------------------------------------------------------ #

def _fwd_v3(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
            *, sm_scale, block_q, block_k, causal, seq_q, seq_k):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = seq_k - seq_q if causal else 0
    d = v_ref.shape[-1]

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)  # (bq, d+128)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    @pl.when(visible)
    def _compute():
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None]).astype(jnp.bfloat16)
        corr = jnp.exp(m_prev - m_new)
        # ones-augmented V: [v | 1 0 ...] so col d of acc accumulates sum(p)
        ones_col = jnp.concatenate(
            [jnp.ones((block_k, 1), jnp.bfloat16),
             jnp.zeros((block_k, 127), jnp.bfloat16)], axis=1
        )
        v_aug = jnp.concatenate([v, ones_col], axis=1)
        acc_scr[:] = acc_scr[:] * corr[:, None] + jnp.dot(
            p, v_aug, preferred_element_type=jnp.float32
        )
        m_scr[:] = m_new[:, None]

    @pl.when(kj == n_k - 1)
    def _finalize():
        l_safe = jnp.maximum(acc_scr[:, d], 1e-30)
        o_ref[:] = (acc_scr[:, :d] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[:] = (m_scr[:, 0] + jnp.log(l_safe))[:, None]


# ------------------------------------------------------------------ #
# v5: H heads per cell + V pre-padded to 128 with a ones column at col d
# (sum(p) rides the p@v matmul for free — the d=64 output wastes those
# MXU lanes anyway and the pad happens ONCE outside the kernel, not per
# block) + exp2 instead of exp (folds log2(e) into the scale).
# ------------------------------------------------------------------ #

_LOG2E = 1.4426950408889634


def _fwd_v5(q_ref, k_ref, vp_ref, o_ref, lse_ref, m_scr, acc_scr,
            *, sm_scale, block_q, block_k, causal, seq_q, seq_k, n_heads, d):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = seq_k - seq_q if causal else 0

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    @pl.when(visible)
    def _compute():
        for h in range(n_heads):
            s = jnp.dot(q_ref[h], k_ref[h].T,
                        preferred_element_type=jnp.float32)
            s = s * (sm_scale * _LOG2E)  # base-2 domain
            if causal:
                s = _causal_mask(s, qi, kj, block_q, block_k, offset)
            m_prev = m_scr[:, h]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p = jnp.exp2(s - m_new[:, None]).astype(jnp.bfloat16)
            corr = jnp.exp2(m_prev - m_new)
            acc_scr[h] = acc_scr[h] * corr[:, None] + jnp.dot(
                p, vp_ref[h], preferred_element_type=jnp.float32
            )
            m_scr[:, h] = m_new

    @pl.when(kj == n_k - 1)
    def _finalize():
        for h in range(n_heads):
            l_safe = jnp.maximum(acc_scr[h][:, d], 1e-30)
            o_ref[h] = (acc_scr[h][:, :d] / l_safe[:, None]).astype(o_ref.dtype)
            lse_ref[h] = ((m_scr[:, h] + jnp.log2(l_safe))
                          * (1.0 / _LOG2E))[:, None]


def fwd_v5_call(q, k, v, *, causal=True, block_q=512, block_k=1024,
                n_heads=2):
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    sm_scale = 1.0 / (d ** 0.5)
    g = bh // n_heads
    q4 = q.reshape(g, n_heads, seq_q, d)
    k4 = k.reshape(g, n_heads, seq_k, d)
    pad = jnp.zeros((bh, seq_k, 64), v.dtype)
    pad = pad.at[:, :, 0].set(1.0)
    vp = jnp.concatenate([v, pad], axis=-1).reshape(g, n_heads, seq_k, d + 64)
    kernel = functools.partial(
        _fwd_v5, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, seq_q=seq_q, seq_k=seq_k, n_heads=n_heads, d=d)
    out, lse = pl.pallas_call(
        kernel,
        grid=(g, seq_q // block_q, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((None, n_heads, block_q, d),
                         lambda b, i, j: (b, 0, i, 0)),
            pl.BlockSpec((None, n_heads, block_k, d),
                         lambda b, i, j: (b, 0, j, 0)),
            pl.BlockSpec((None, n_heads, block_k, d + 64),
                         lambda b, i, j: (b, 0, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, n_heads, block_q, d),
                         lambda b, i, j: (b, 0, i, 0)),
            pl.BlockSpec((None, n_heads, block_q, 1),
                         lambda b, i, j: (b, 0, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q4.shape, q.dtype),
            jax.ShapeDtypeStruct((g, n_heads, seq_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, n_heads), jnp.float32),
            pltpu.VMEM((n_heads, block_q, d + 64), jnp.float32),
        ],
    )(q4, k4, vp)
    return out.reshape(bh, seq_q, d)


# ------------------------------------------------------------------ #
# v4: v2 with two heads per grid cell (python-unrolled)
# ------------------------------------------------------------------ #

def _fwd_v4(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
            *, sm_scale, block_q, block_k, causal, seq_q, seq_k):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = seq_k - seq_q if causal else 0

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    @pl.when(visible)
    def _compute():
        for h in range(2):
            q = q_ref[h]
            k = k_ref[h]
            v = v_ref[h]
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
            if causal:
                s = _causal_mask(s, qi, kj, block_q, block_k, offset)
            m_prev = m_scr[:, h]
            l_prev = l_scr[:, h]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[:, None])
            corr = jnp.exp(m_prev - m_new)
            l_scr[:, h] = l_prev * corr + jnp.sum(p, axis=-1)
            acc_scr[h] = acc_scr[h] * corr[:, None] + jnp.dot(
                p.astype(jnp.bfloat16), v, preferred_element_type=jnp.float32
            )
            m_scr[:, h] = m_new

    @pl.when(kj == n_k - 1)
    def _finalize():
        for h in range(2):
            l_safe = jnp.maximum(l_scr[:, h], 1e-30)
            o_ref[h] = (acc_scr[h] / l_safe[:, None]).astype(o_ref.dtype)
            lse_ref[h] = ((m_scr[:, h] + jnp.log(l_safe)))[:, None]


def fwd_call(version, q, k, v, *, causal=True, block_q=512, block_k=1024):
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    sm_scale = 1.0 / (d ** 0.5)
    if version == "v4":
        grid = (bh // 2, seq_q // block_q, seq_k // block_k)
        kernel = functools.partial(
            _fwd_v4, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
            causal=causal, seq_q=seq_q, seq_k=seq_k)
        q4 = q.reshape(bh // 2, 2, seq_q, d)
        k4 = k.reshape(bh // 2, 2, seq_k, d)
        v4 = v.reshape(bh // 2, 2, seq_k, d)
        out, lse = pl.pallas_call(
            kernel, grid=grid,
            in_specs=[
                pl.BlockSpec((None, 2, block_q, d), lambda b, i, j: (b, 0, i, 0)),
                pl.BlockSpec((None, 2, block_k, d), lambda b, i, j: (b, 0, j, 0)),
                pl.BlockSpec((None, 2, block_k, d), lambda b, i, j: (b, 0, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, 2, block_q, d), lambda b, i, j: (b, 0, i, 0)),
                pl.BlockSpec((None, 2, block_q, 1), lambda b, i, j: (b, 0, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(q4.shape, q.dtype),
                jax.ShapeDtypeStruct((bh // 2, 2, seq_q, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, 2), jnp.float32),
                pltpu.VMEM((block_q, 2), jnp.float32),
                pltpu.VMEM((2, block_q, d), jnp.float32),
            ],
        )(q4, k4, v4)
        return out.reshape(bh, seq_q, d)

    kernel_fn = {"v2": _fwd_v2, "v3": _fwd_v3}[version]
    grid = (bh, seq_q // block_q, seq_k // block_k)
    kernel = functools.partial(
        kernel_fn, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, seq_q=seq_q, seq_k=seq_k)
    acc_w = d + 128 if version == "v3" else d
    scratch = [
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, acc_w), jnp.float32),
    ]
    out, lse = pl.pallas_call(
        kernel, grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        scratch_shapes=scratch,
    )(q, k, v)
    return out


# ------------------------------------------------------------------ #
# Timing
# ------------------------------------------------------------------ #

def visible_fraction(seq_q, seq_k, block_q, block_k, causal):
    if not causal:
        return 1.0
    nq, nk = seq_q // block_q, seq_k // block_k
    offset = seq_k - seq_q
    vis = sum(
        1
        for qi in range(nq)
        for kj in range(nk)
        if qi * block_q + block_q - 1 + offset >= kj * block_k
    )
    return vis / (nq * nk)


def timed(fn, args, K1=4, K2=16):
    """Slope-fit device ms per call of fn(*args) -> array like args[0]."""

    def chain(K):
        @jax.jit
        def run(q, k, v):
            def body(c, _):
                return fn(c, k, v), ()
            o, _ = lax.scan(body, q, None, length=K)
            return jnp.float32(o.astype(jnp.float32).sum())
        return run

    r1, r2 = chain(K1), chain(K2)
    float(r1(*args))  # compile + warm
    float(r2(*args))
    best = []
    for r, K in ((r1, K1), (r2, K2)):
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            f = float(r(*args))
            ts.append(time.perf_counter() - t0)
        best.append(min(ts))
    return (best[1] - best[0]) / (K2 - K1) * 1e3  # ms/call


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind}", file=sys.stderr)
    peak = 197e12  # v5e bf16

    bh, s, d = 96, 2048, 64  # lm_base: b=8, h=12
    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (bh, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (bh, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (bh, s, d), jnp.bfloat16)

    block_q, block_k = 512, 1024
    vis = visible_fraction(s, s, block_q, block_k, True)
    # executed fwd dots: 2 dots x 2*s*s*d per bh, over visible blocks
    fwd_flops = bh * 2 * 2.0 * s * s * d * vis

    sys.path.insert(0, "/root/repo")
    from ddp_practice_tpu.ops.flash_attention import flash_attention_with_lse

    def v1(q, k, v):
        o, _ = flash_attention_with_lse(q, k, v, causal=True)
        return o

    results = {}
    # numerics check vs v1 first
    ref = v1(q, k, v)
    for name in ("v2", "v3", "v4"):
        got = fwd_call(name, q, k, v)
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        print(f"{name} max abs diff vs v1: {err:.2e}", file=sys.stderr)

    for name in ("v5h2", "v5h4"):
        nh = int(name[-1])
        got = fwd_v5_call(q, k, v, n_heads=nh)
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        print(f"{name} max abs diff vs v1: {err:.2e}", file=sys.stderr)

    cases = [
        ("v1_fp32", v1),
        ("v2_bf16", lambda q, k, v: fwd_call("v2", q, k, v)),
        ("v4_2head", lambda q, k, v: fwd_call("v4", q, k, v)),
        ("v5h2", lambda q, k, v: fwd_v5_call(q, k, v, n_heads=2)),
        ("v5h4", lambda q, k, v: fwd_v5_call(q, k, v, n_heads=4)),
        ("v5h2_bq1024", lambda q, k, v: fwd_v5_call(
            q, k, v, n_heads=2, block_q=1024, block_k=1024)),
        ("v5h2_bk2048", lambda q, k, v: fwd_v5_call(
            q, k, v, n_heads=2, block_q=256, block_k=2048)),
    ]
    for name, fn in cases:
        if name.endswith("bq1024"):
            vis_c = visible_fraction(s, s, 1024, 1024, True)
        elif name.endswith("bk2048"):
            vis_c = visible_fraction(s, s, 256, 2048, True)
        else:
            vis_c = vis
        flops_c = bh * 2 * 2.0 * s * s * d * vis_c
        ms = timed(fn, (q, k, v))
        tflops = flops_c / (ms / 1e3) / 1e12
        results[name] = (ms, tflops)
        print(f"fwd {name:14s}: {ms:7.3f} ms  {tflops:6.1f} TFLOP/s "
              f"({100*tflops*1e12/peak:.1f}% of bf16 peak, "
              f"executed-dot basis)")

    return results


if __name__ == "__main__":
    main()
