"""Ablate the fused encoder-layer FORWARD kernel's components on the chip
(the probe behind the BENCHMARKS.md fused-kernel cost attribution).

Each variant monkeypatches one nonlinearity out of _fwd_core (identity /
cheap substitute) and times the forward kernel alone with xprof device
time; the delta against the full kernel is that component's serial cost.
_core_patched mirrors the CURRENT production core (concat projection,
seq_merge honored) so deltas isolate exactly one component. Numerics are
wrong in ablated variants — this is a timing probe only.
"""
import functools
import shutil
import sys
import tempfile

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from ddp_practice_tpu.ops import fused_encoder as fe
from ddp_practice_tpu.utils.xprof import op_summary


def device_ms(fn, *args, reps=8):
    out = fn(*args)
    jax.block_until_ready(out)
    out = fn(*args)
    jax.block_until_ready(out)
    tmp = tempfile.mkdtemp(prefix="xp_fa_")
    with jax.profiler.trace(tmp):
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
    s = op_summary(tmp)
    shutil.rmtree(tmp, ignore_errors=True)
    return s["total_ps"] / 1e9 / reps


def make_params(key, d, mlp, h):
    ks = jax.random.split(key, 8)
    n = jax.nn.initializers.normal(0.02)
    return {
        "ln1": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
        "attn": {
            "qkv": {"kernel": n(ks[0], (d, 3, h, d // h)),
                    "bias": jnp.zeros((3, h, d // h))},
            "out": {"kernel": n(ks[1], (h, d // h, d)),
                    "bias": jnp.zeros((d,))},
        },
        "ln2": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
        "mlp": {
            "fc_in": {"kernel": n(ks[2], (d, mlp)), "bias": jnp.zeros((mlp,))},
            "fc_out": {"kernel": n(ks[3], (mlp, d)), "bias": jnp.zeros((d,))},
        },
    }


def main():
    b, s, d, h, mlp = 1024, 64, 192, 3, 768
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.fold_in(key, 1), (b, s, d), jnp.bfloat16)
    params = make_params(key, d, mlp, h)
    fwd = jax.jit(functools.partial(
        fe.fused_encoder_forward, num_heads=h, compute_dtype=jnp.bfloat16))

    flops_fwd = b * s * (
        2 * d * 3 * d + 2 * 2 * s * (d // h) * h + 2 * d * d
        + 2 * 2 * d * mlp)

    base = device_ms(fwd, x, params)
    print(f"full fwd kernel: {base:.3f} ms  "
          f"({flops_fwd / (base * 1e-3) / 1e12:.1f} TF/s, "
          f"{flops_fwd / (base * 1e-3) / 197e12 * 100:.1f}% MFU)")

    orig_core = fe._fwd_core

    def run_variant(name, patch):
        patch()
        try:
            t = device_ms(jax.jit(functools.partial(
                fe.fused_encoder_forward, num_heads=h,
                compute_dtype=jnp.bfloat16)), x, params)
            print(f"{name:28s} {t:.3f} ms   delta {base - t:+.3f}")
        finally:
            fe._fwd_core = orig_core
        return t

    # 1. gelu -> identity (keeps both matmuls)
    def no_gelu():
        def core(*a, **k):
            return _core_patched(*a, gelu="id", **k)
        fe._fwd_core = core
    # 2. softmax -> scale only
    def no_softmax():
        def core(*a, **k):
            return _core_patched(*a, softmax="id", **k)
        fe._fwd_core = core
    # 3. LN -> affine only (no mean/var/rsqrt)
    def no_ln():
        def core(*a, **k):
            return _core_patched(*a, ln="id", **k)
        fe._fwd_core = core
    # 4. all three off: the pure-matmul skeleton
    def matmul_only():
        def core(*a, **k):
            return _core_patched(*a, gelu="id", softmax="id", ln="id", **k)
        fe._fwd_core = core

    def _core_patched(xt, imgs, s_, ln1_s, ln1_b, wqkv, bqkv, wproj, bproj,
                      ln2_s, ln2_b, w_in, b_in, w_out, b_out,
                      *, num_heads, head_dim, compute_dtype, causal=False,
                      seq_merge=1, gelu="full", softmax="full", ln="full"):
        cd = compute_dtype
        f32 = jnp.float32
        t, dd = xt.shape
        hh, hd = num_heads, head_dim

        def LN(v, sc, bi):
            if ln == "id":
                return v * sc + bi, v, jnp.ones((t, 1), f32)
            return fe._layer_norm(v, sc, bi)

        y1a, y1hat, r1 = LN(xt, ln1_s, ln1_b)
        qkv = (fe._mm(y1a, wqkv, cd) + bqkv).astype(cd)
        sc_ = 1.0 / (hd ** 0.5)
        m = seq_merge
        im, sm = imgs // m, s_ * m
        penalty = None
        if m > 1:
            qpos = jax.lax.broadcasted_iota(jnp.int32, (sm, sm), 0)
            kpos = jax.lax.broadcasted_iota(jnp.int32, (sm, sm), 1)
            penalty = jnp.where(
                (qpos // s_) == (kpos // s_), 0.0, -1e30)[None]
        heads = []
        outs = []
        for hi in range(hh):
            def head_slice(base):
                col = base + hi * hd
                return qkv[:, col: col + hd].reshape(im, sm, hd)
            q = head_slice(0)
            k = head_slice(hh * hd)
            v = head_slice(2 * hh * hd)
            scores = fe._bdot(q, k, 2, 2, cd) * sc_
            if penalty is not None:
                scores = scores + penalty
            if softmax == "id":
                p = scores
            else:
                scores = scores - jnp.max(scores, axis=-1, keepdims=True)
                p = jnp.exp(scores)
                p = p / jnp.sum(p, axis=-1, keepdims=True)
            o = fe._bdot(p, v, 2, 1, cd)
            outs.append(o.reshape(t, hd))
            heads.append((q, k, v, p))
        o_all = jnp.concatenate(outs, axis=1)
        x2 = xt + fe._mm(o_all, wproj, cd) + bproj
        y2a, y2hat, r2 = LN(x2, ln2_s, ln2_b)
        hpre = fe._mm(y2a, w_in, cd) + b_in
        if gelu == "id":
            tanh = hpre
            hg = hpre.astype(cd)
        else:
            tanh = jnp.tanh(fe._GELU_C * (
                hpre + fe._GELU_A * hpre * hpre * hpre))
            hg = (0.5 * hpre * (1.0 + tanh)).astype(cd)
        out = x2 + fe._mm(hg, w_out, cd) + b_out
        return dict(y1a=y1a, y1hat=y1hat, r1=r1, qkv=qkv, heads=heads,
                    o_all=o_all, x2=x2, y2a=y2a, y2hat=y2hat, r2=r2,
                    hpre=hpre, tanh=tanh, hg=hg, out=out)

    run_variant("gelu -> identity", no_gelu)
    run_variant("softmax -> identity", no_softmax)
    run_variant("LN -> affine only", no_ln)
    run_variant("matmul skeleton only", matmul_only)


if __name__ == "__main__":
    main()
