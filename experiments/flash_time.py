"""Time the restructured flash kernels (fwd, fwd+bwd) vs the bundled jax
TPU kernel at lm_base shapes. Slope-fit over K in {16, 64} chained scans,
min of 5 reps, scalar-readback fenced."""
import functools
import sys
import time

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, "/root/repo")

PEAK = 197e12


def timed(fn, args, K1=16, K2=64):
    def chain(K):
        @jax.jit
        def run(q, k, v):
            def body(c, _):
                return fn(c, k, v), ()
            o, _ = lax.scan(body, q, None, length=K)
            return jnp.float32(o.astype(jnp.float32).sum())
        return run

    r1, r2 = chain(K1), chain(K2)
    float(r1(*args)); float(r2(*args))
    best = []
    for r in (r1, r2):
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(r(*args))
            ts.append(time.perf_counter() - t0)
        best.append(min(ts))
    return (best[1] - best[0]) / (K2 - K1) * 1e3


def main():
    from ddp_practice_tpu.ops.flash_attention import flash_attention_with_lse
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as jax_flash)

    bh, s, d = 96, 2048, 64
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (bh, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (bh, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (bh, s, d), jnp.bfloat16)

    def ours_fwd(q, k, v):
        o, _ = flash_attention_with_lse(q, k, v, causal=True)
        return o

    def ours_fwdbwd(q, k, v):
        f = lambda q: flash_attention_with_lse(q, k, v, causal=True)[0].sum()
        return jax.grad(f)(q)

    bs = BlockSizes(
        block_q=512, block_k_major=1024, block_k=1024, block_b=1,
        block_q_major_dkv=512, block_k_major_dkv=1024,
        block_k_dkv=1024, block_q_dkv=512,
        block_k_major_dq=1024, block_k_dq=1024, block_q_dq=512,
    )

    def official_fwd(q, k, v):
        o = jax_flash(q.reshape(8, 12, s, d), k.reshape(8, 12, s, d),
                      v.reshape(8, 12, s, d), causal=True,
                      sm_scale=1.0 / d ** 0.5, block_sizes=bs)
        return o.reshape(bh, s, d)

    def official_fwdbwd(q, k, v):
        f = lambda q: official_fwd(q, k, v).sum()
        return jax.grad(f)(q)

    # executed-dot flops at blocks (512, 1024), causal
    vis = 6 / 8
    fwd_fl = bh * 2 * 2.0 * s * s * d * vis
    bwd_fl = bh * 7 * 2.0 * s * s * d * vis  # s,dv,dp,dk + s,dp,dq

    for name, fn, fl in [
        ("ours fwd", ours_fwd, fwd_fl),
        ("jaxk fwd", official_fwd, fwd_fl),
        ("ours fwd+bwd", ours_fwdbwd, fwd_fl + bwd_fl),
        ("jaxk fwd+bwd", official_fwdbwd, fwd_fl + bwd_fl),
    ]:
        ms = timed(fn, (q, k, v))
        tf = fl / (ms / 1e3) / 1e12
        print(f"{name:14s}: {ms:7.3f} ms   executed {tf:6.1f} TF/s"
              f"  ({100 * tf * 1e12 / PEAK:.1f}% of bf16 peak)")


if __name__ == "__main__":
    main()
