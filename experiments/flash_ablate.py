"""Where does the non-MXU half of the flash fwd cell go? Ablations at
lm_base shapes (bh=96, s=2048, d=64) on the real chip:

  causal        — v2 kernel as-is (mask + max + exp + sum)
  noncausal     — mask pass removed (all blocks visible: more dot FLOPs,
                  but no iota/where passes)
  nomax         — causal but rowmax pass removed (UNSAFE numerics — cost
                  probe only)
  jax_official  — jax.experimental.pallas.ops.tpu.flash_attention at the
                  same shapes/blocks (what Google's hand-tuned kernel
                  achieves on this chip = the practical ceiling)
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")
from experiments.flash_variants import (
    _fwd_v2, fwd_call, timed, visible_fraction, _causal_mask, _NEG_INF)


def _fwd_nomax(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
               *, sm_scale, block_q, block_k, causal, seq_q, seq_k):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = seq_k - seq_q if causal else 0

    @pl.when(kj == 0)
    def _init():
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    @pl.when(visible)
    def _compute():
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        p = jnp.exp(s)  # UNSAFE: no running max — cost probe only
        l_scr[:] = (l_scr[:, 0] + jnp.sum(p, axis=-1))[:, None]
        acc_scr[:] = acc_scr[:] + jnp.dot(
            p.astype(jnp.bfloat16), v, preferred_element_type=jnp.float32
        )

    @pl.when(kj == n_k - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[:] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[:] = jnp.log(l_safe)[:, None]


def nomax_call(q, k, v, *, block_q=512, block_k=1024):
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    kernel = functools.partial(
        _fwd_nomax, sm_scale=1.0 / d ** 0.5, block_q=block_q,
        block_k=block_k, causal=True, seq_q=seq_q, seq_k=seq_k)
    out, _ = pl.pallas_call(
        kernel,
        grid=(bh, seq_q // block_q, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )(q, k, v)
    return out


def main():
    peak = 197e12
    bh, s, d = 96, 2048, 64
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (bh, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (bh, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (bh, s, d), jnp.bfloat16)

    vis = visible_fraction(s, s, 512, 1024, True)

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as jax_flash)

    q4 = q.reshape(8, 12, s, d)
    k4 = k.reshape(8, 12, s, d)
    v4 = v.reshape(8, 12, s, d)

    def official(q, k, v, *, bq=512, bk=1024):
        bs = BlockSizes(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk,
            block_k_dkv=bk, block_q_dkv=bq,
            block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
        )
        o = jax_flash(q.reshape(8, 12, s, d), k.reshape(8, 12, s, d),
                      v.reshape(8, 12, s, d), causal=True,
                      sm_scale=1.0 / d ** 0.5, block_sizes=bs)
        return o.reshape(bh, s, d)

    cases = [
        ("causal_v2", lambda q, k, v: fwd_call("v2", q, k, v), vis),
        ("noncausal_v2", lambda q, k, v: fwd_call(
            "v2", q, k, v, causal=False), 1.0),
        ("nomax", nomax_call, vis),
        ("jax_official", official, vis),
        ("jax_official_b256_512", functools.partial(official, bq=256, bk=512),
         visible_fraction(s, s, 256, 512, True)),
    ]
    for name, fn, vfrac in cases:
        flops = bh * 2 * 2.0 * s * s * d * vfrac
        ms = timed(fn, (q, k, v))
        tflops = flops / (ms / 1e3) / 1e12
        useful = bh * 2 * 2.0 * s * s * d * 0.5 / (ms / 1e3) / 1e12
        print(f"fwd {name:22s}: {ms:7.3f} ms  executed {tflops:6.1f} TF/s "
              f"({100*tflops*1e12/peak:.1f}%)  useful {useful:5.1f} TF/s "
              f"({100*useful*1e12/peak:.1f}%)")


if __name__ == "__main__":
    main()
