"""Controlled A/B of the round-4 1F1B phase split (fill/steady/drain
scans vs one masked scan): same process, same 8-virtual-CPU mesh, same
model and inputs, many fenced reps, median wall-clock per step.

The full bench_pipeline comparison on this CPU mesh is +/-20%+ noisy
across runs (BENCHMARKS.md); importing the round-3 module side by side
removes every variable except the schedule structure."""
import importlib.util
import statistics
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, "/root/repo")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def load_old(path="/tmp/old_1f1b.py"):
    spec = importlib.util.spec_from_file_location("old_1f1b", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(mbs=(4, 8), reps=7):
    from ddp_practice_tpu.config import MeshConfig, PrecisionPolicy, TrainConfig
    from ddp_practice_tpu.models import create_model
    import ddp_practice_tpu.models.pipeline_lm as plm
    import ddp_practice_tpu.parallel.pipeline_1f1b as new_mod
    from ddp_practice_tpu.parallel.mesh import batch_sharding, build_mesh, shard_state
    from ddp_practice_tpu.parallel.ring import set_current_mesh
    from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import make_lm_train_step

    old_mod = load_old()
    P_, dp = 4, 2
    seq, vocab = 128, 256
    for M in mbs:
        mesh = build_mesh(MeshConfig(data=dp, pipe=P_))
        set_current_mesh(mesh)
        policy = PrecisionPolicy.from_name("bf16")
        model = create_model(
            "lm_pipe", policy=policy, vocab_size=vocab, max_len=seq,
            hidden_dim=256, depth=4, num_heads=8, mlp_dim=1024,
            num_stages=P_, num_microbatches=M, schedule="1f1b",
        )
        tx = make_optimizer(TrainConfig(optimizer="adamw", learning_rate=1e-3))
        b = M * 4 * dp
        sample = jnp.zeros((b, seq), jnp.int32)
        init_fn = lambda r: create_state(model, tx, rng=r, sample_input=sample)
        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        sh = shard_state(abstract, mesh, param_sharding_rules("lm_pipe"))
        jinit = jax.jit(init_fn, out_shardings=sh)
        batch = {"tokens": jnp.asarray(
            np.random.default_rng(0).integers(0, vocab, (b, seq + 1)),
            jnp.int32)}

        results = {}
        for name, mod in (("old", old_mod), ("new", new_mod)):
            plm.__dict__.pop("pipeline_1f1b_loss_and_grad", None)
            # the model imports the fn inside its method; patch the module
            # the import resolves to
            sys.modules["ddp_practice_tpu.parallel.pipeline_1f1b"] = mod
            step = make_lm_train_step(
                model, tx, mesh=mesh, state_shardings=sh,
                batch_shardings=batch_sharding(mesh),
            )
            state = jinit(jax.random.PRNGKey(0))  # fresh buffers: the
            # step donates its state, so variants must not share arrays
            state, m = step(state, batch)  # compile
            _ = float(m["loss"])
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                state, m = step(state, batch)
                _ = float(m["loss"])
                ts.append(time.perf_counter() - t0)
            results[name] = (statistics.median(ts), float(m["loss"]))
        sys.modules["ddp_practice_tpu.parallel.pipeline_1f1b"] = new_mod
        o, n = results["old"], results["new"]
        print(f"M={M}: old {o[0]*1e3:8.1f} ms/step  new {n[0]*1e3:8.1f} "
              f"ms/step  speedup {o[0]/n[0]:.2f}x  "
              f"loss old/new {o[1]:.6f}/{n[1]:.6f}")
        set_current_mesh(None)


if __name__ == "__main__":
    main()
