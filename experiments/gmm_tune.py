"""Microbench megablox gmm at the lm_moe sorted-path shape: find a
tiling/dtype configuration that runs near the dense-matmul roofline, or
prove the kernel can't and motivate an alternative."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))


def bench(fn, *args, iters=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    from jax.experimental.pallas.ops.tpu.megablox.gmm import gmm

    m, k, n, e = 32768, 768, 3072, 8
    key = jax.random.PRNGKey(0)
    lhs = jax.random.normal(key, (m, k), jnp.bfloat16)
    rhs = jax.random.normal(key, (e, k, n), jnp.bfloat16)
    # balanced groups
    gs = jnp.full((e,), m // e, jnp.int32)
    flops = 2 * m * k * n

    # dense reference: one (m,k)x(k,n) matmul of the same total FLOPs
    dense = jax.jit(lambda a, b: jax.lax.dot(a, b,
                    preferred_element_type=jnp.float32).astype(jnp.bfloat16))
    ms = bench(dense, lhs, rhs[0])
    print(f"dense {ms:7.3f} ms  {flops/ms/1e9:8.1f} GFLOP/s")

    for tiling in [(128, 128, 128), (512, 128, 128), (128, 128, 512),
                   (512, 768, 512), (256, 256, 256), (512, 512, 512),
                   (1024, 768, 1024), (2048, 768, 1024)]:
        for pet in (jnp.bfloat16, jnp.float32):
            try:
                f = jax.jit(lambda a, b, g, t=tiling, p=pet: gmm(
                    a, b, g, preferred_element_type=p, tiling=t))
                ms = bench(f, lhs, rhs, gs)
                print(f"gmm tiling={tiling} pet={pet.__name__}: "
                      f"{ms:7.3f} ms  {flops/ms/1e9:8.1f} GFLOP/s")
            except Exception as ex:
                print(f"gmm tiling={tiling} pet={pet.__name__}: FAIL "
                      f"{type(ex).__name__} {str(ex)[:120]}")


if __name__ == "__main__":
    main()
