"""Profile the lm_moe bench step (per-op device time) via utils/xprof —
the round-4 method, pointed at the MoE dispatch/combine glue (round-5
verdict item 2: lm_moe 37.66% MFU vs dense lm_long 47.27%)."""
import shutil
import sys
import tempfile

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))


def main():
    from ddp_practice_tpu.config import MeshConfig, PrecisionPolicy, TrainConfig
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.parallel.mesh import (
        batch_sharding, build_mesh, replicated, shard_state)
    from ddp_practice_tpu.parallel.ring import set_current_mesh
    from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import _lm_train_step_fn
    from ddp_practice_tpu.utils.xprof import op_summary

    # the bench.py lm_moe entry's exact dims
    seq_len, vocab, bsz, K = 2048, 32768, 8, 4
    model_kwargs = dict(
        hidden_dim=768, depth=12, num_heads=12, mlp_dim=3072,
        moe_every=2, num_experts=8, moe_group_size=256,
        capacity_factor=1.5,
    )
    mesh = build_mesh(MeshConfig(data=-1))
    set_current_mesh(mesh)
    policy = PrecisionPolicy.from_name("bf16")
    model = create_model("lm_moe", policy=policy, vocab_size=vocab,
                         max_len=seq_len, attn_impl="flash", **model_kwargs)
    tcfg = TrainConfig(model="lm_moe", optimizer="adamw", learning_rate=3e-4)
    tx = make_optimizer(tcfg)
    sample = jnp.zeros((bsz, seq_len), jnp.int32)
    abstract = jax.eval_shape(
        lambda r: create_state(model, tx, rng=r, sample_input=sample),
        jax.random.PRNGKey(0))
    shardings = shard_state(abstract, mesh, param_sharding_rules("lm_moe"))
    state = jax.jit(
        lambda r: create_state(model, tx, rng=r, sample_input=sample),
        out_shardings=shardings)(jax.random.PRNGKey(0))

    step_fn = _lm_train_step_fn(model, tx, with_accuracy=False)
    bsh = batch_sharding(mesh)
    rep = replicated(mesh)
    base_key = jax.random.PRNGKey(1)

    def chunk(state):
        def body(st, key):
            tokens = jax.random.randint(
                key, (bsz, seq_len + 1), 0, vocab, dtype=jnp.int32)
            batch = {"tokens": lax.with_sharding_constraint(tokens, bsh)}
            return step_fn(st, batch)
        keys = jax.random.split(jax.random.fold_in(base_key, state.step), K)
        state, ms = lax.scan(body, state, keys)
        return state, jax.tree.map(lambda v: v[-1], ms)

    jchunk = jax.jit(chunk, donate_argnums=0, in_shardings=(shardings,),
                     out_shardings=(shardings, rep))
    state, m = jchunk(state)
    _ = float(m["loss"])
    state, m = jchunk(state)
    _ = float(m["loss"])

    tmp = tempfile.mkdtemp(prefix="xp_moe_")
    with jax.profiler.trace(tmp):
        state, m = jchunk(state)
        _ = float(m["loss"])
    s = op_summary(tmp)
    total = s["total_ps"] / 1e9 / K
    print(f"device op time: {total:.2f} ms/step ({K} steps)")
    cats = sorted(s["categories"].items(), key=lambda kv: -kv[1]["ps"])
    for cat, v in cats[:10]:
        print(f"  {v['ps']/1e9/K:7.2f} ms/step  {cat}")
    for (cat, nm), ps in sorted(s["ops"].items(), key=lambda kv: -kv[1])[:30]:
        print(f"  {ps/1e9/K:7.3f} ms/step  [{cat}] {nm[:78]}")
    print("---- glue categories ----")
    for (cat, nm), ps in sorted(s["ops"].items(), key=lambda kv: -kv[1]):
        if cat in ("custom fusion", "loop fusion", "data formatting",
                   "pad", "sort", "non-fusion elementwise") and (
                       ps / 1e9 / K > 0.15):
            print(f"  {ps/1e9/K:7.3f} ms/step  [{cat}] {nm[:78]}")
    shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
