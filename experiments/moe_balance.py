"""Router balance trajectory vs aux-loss weight (VERDICT item 3).
Trains lm_moe on the chip and prints drop/load health per chunk."""
import sys

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, "/root/repo")


def run(aux_weight, chunks=16, K=8, cf=1.25, bias_rate=0.02, structured=False, corpus=False):
    from ddp_practice_tpu.config import MeshConfig, PrecisionPolicy, TrainConfig
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.parallel.mesh import (
        batch_sharding, build_mesh, replicated, shard_state)
    from ddp_practice_tpu.parallel.ring import set_current_mesh
    from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import _lm_train_step_fn

    seq, vocab, bsz = 2048, 32768, 8
    corpus_windows = None
    if corpus:
        # the CLI's synthetic byte corpus (order-1 Markov, data/lm_corpus):
        # embeddings see every token thousands of times, so the router's
        # inputs stabilize — the regime the balance machinery targets
        from ddp_practice_tpu.data.lm_corpus import synthetic_token_corpus
        import numpy as np
        c = synthetic_token_corpus(n_tokens=1 << 20)
        vocab = c.vocab_size
        corpus_windows = jnp.asarray(c.windows(seq))
    mesh = build_mesh(MeshConfig(data=-1))
    set_current_mesh(mesh)
    policy = PrecisionPolicy.from_name("bf16")
    model = create_model("lm_moe", policy=policy, vocab_size=vocab,
                         max_len=seq, attn_impl="flash",
                         moe_aux_weight=aux_weight, capacity_factor=cf,
                         moe_bias_rate=bias_rate,
                         hidden_dim=768, depth=12, num_heads=12,
                         mlp_dim=3072, num_experts=8)
    tx = make_optimizer(TrainConfig(model="lm_moe", optimizer="adamw",
                                    learning_rate=3e-4))
    sample = jnp.zeros((bsz, seq), jnp.int32)
    init_fn = lambda r: create_state(model, tx, rng=r, sample_input=sample)
    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    sh = shard_state(abstract, mesh, param_sharding_rules("lm_moe"))
    state = jax.jit(init_fn, out_shardings=sh)(jax.random.PRNGKey(0))
    step_fn = _lm_train_step_fn(model, tx)
    bsh = batch_sharding(mesh)
    rep = replicated(mesh)
    base = jax.random.PRNGKey(1)

    def chunk(state):
        def body(st, key):
            if corpus_windows is not None:
                idx = jax.random.randint(key, (bsz,), 0,
                                         corpus_windows.shape[0], jnp.int32)
                toks = corpus_windows[idx]
            elif structured:
                # corpus-like stream: per-sequence topic offset + narrow
                # in-topic vocabulary + positional drift — gives the
                # router content to separate on, unlike uniform noise
                k1, k2 = jax.random.split(key)
                topic = jax.random.randint(k1, (bsz, 1), 0, vocab // 64,
                                           dtype=jnp.int32) * 64
                toks = (topic + jax.random.randint(
                    k2, (bsz, seq + 1), 0, 64, dtype=jnp.int32)) % vocab
            else:
                toks = jax.random.randint(key, (bsz, seq + 1), 0, vocab,
                                          dtype=jnp.int32)
            return step_fn(st, {"tokens": lax.with_sharding_constraint(
                toks, bsh)})
        keys = jax.random.split(jax.random.fold_in(base, state.step), K)
        st, ms = lax.scan(body, state, keys)
        return st, jax.tree.map(lambda v: v[-1], ms)

    jchunk = jax.jit(chunk, donate_argnums=0, in_shardings=(sh,),
                     out_shardings=(sh, rep))
    print(f"--- aux {aux_weight} cf {cf} bias_rate {bias_rate} structured {structured} corpus {corpus} vocab {vocab} ---")
    for _ in range(chunks):
        state, m = jchunk(state)
        print(f"step {int(state.step):4d}: loss {float(m['loss']):.4f} "
              f"drop {float(m['moe_drop_rate']):.4f} "
              f"load_min {float(m['moe_load_min']):.4f} "
              f"load_max {float(m['moe_load_max']):.4f}")
    set_current_mesh(None)


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--aux", type=float, default=0.01)
    ap.add_argument("--cf", type=float, default=2.0)
    ap.add_argument("--bias_rate", type=float, default=0.02)
    ap.add_argument("--structured", action="store_true")
    ap.add_argument("--corpus", action="store_true")
    ap.add_argument("--chunks", type=int, default=16)
    a = ap.parse_args()
    run(a.aux, cf=a.cf, bias_rate=a.bias_rate, structured=a.structured,
        corpus=a.corpus, chunks=a.chunks)
