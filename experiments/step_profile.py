"""Per-op xprof decomposition of a bench-config train step.

    python experiments/step_profile.py vit_base    # bs=192 headline step
    python experiments/step_profile.py resnet50    # bs=128 at 224^2

Backs the round-5 BENCHMARKS.md decompositions (ViT-Base headline /
ResNet-50 accounting).
"""
import os
import shutil
import sys
import tempfile

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONFIGS = {
    "vit_base": dict(bsz=192, K=8, shape=(32, 32, 3), num_classes=10),
    "resnet50": dict(bsz=128, K=4, shape=(224, 224, 3), num_classes=1000),
}


def main(name: str):
    from ddp_practice_tpu.config import MeshConfig, PrecisionPolicy, TrainConfig
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.parallel.mesh import (
        batch_sharding, build_mesh, replicated, shard_state)
    from ddp_practice_tpu.parallel.ring import set_current_mesh
    from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
    from ddp_practice_tpu.train.state import create_state, make_optimizer
    from ddp_practice_tpu.train.steps import _train_step_fn
    from ddp_practice_tpu.utils.xprof import op_summary

    cfg = CONFIGS[name]
    bsz, K, shape, ncls = cfg["bsz"], cfg["K"], cfg["shape"], cfg["num_classes"]
    mesh = build_mesh(MeshConfig(data=-1))
    set_current_mesh(mesh)
    policy = PrecisionPolicy.from_name("bf16")
    model = create_model(name, policy=policy, num_classes=ncls)
    tcfg = TrainConfig(model=name, optimizer="adamw", learning_rate=3e-4)
    tx = make_optimizer(tcfg)
    sample = jnp.zeros((bsz,) + shape, jnp.float32)
    abstract = jax.eval_shape(
        lambda r: create_state(model, tx, rng=r, sample_input=sample),
        jax.random.PRNGKey(0))
    shardings = shard_state(abstract, mesh, param_sharding_rules(name))
    state = jax.jit(
        lambda r: create_state(model, tx, rng=r, sample_input=sample),
        out_shardings=shardings)(jax.random.PRNGKey(0))

    step_fn = _train_step_fn(model, tx, label_smoothing=0.0)
    bsh = batch_sharding(mesh)
    rep = replicated(mesh)
    base_key = jax.random.PRNGKey(1)

    def chunk(state):
        def body(st, key):
            imgs = jax.random.uniform(key, (bsz,) + shape, jnp.float32)
            lbls = jax.random.randint(key, (bsz,), 0, ncls, jnp.int32)
            batch = {
                "image": lax.with_sharding_constraint(imgs, bsh),
                "label": lax.with_sharding_constraint(lbls, bsh),
            }
            return step_fn(st, batch)
        keys = jax.random.split(jax.random.fold_in(base_key, state.step), K)
        state, ms = lax.scan(body, state, keys)
        return state, jax.tree.map(lambda v: v[-1], ms)

    jchunk = jax.jit(chunk, donate_argnums=0, in_shardings=(shardings,),
                     out_shardings=(shardings, rep))
    state, m = jchunk(state)
    _ = float(m["loss"])
    state, m = jchunk(state)
    _ = float(m["loss"])

    tmp = tempfile.mkdtemp(prefix=f"xp_{name}_")
    with jax.profiler.trace(tmp):
        state, m = jchunk(state)
        _ = float(m["loss"])
    s = op_summary(tmp)
    total = s["total_ps"] / 1e9 / K
    print(f"device op time: {total:.3f} ms/step ({K} steps)")
    cats = sorted(s["categories"].items(), key=lambda kv: -kv[1]["ps"])
    for cat, v in cats:
        ms = v["ps"] / 1e9 / K
        if ms > 0.005:
            print(f"  {ms:7.3f} ms/step  {cat}  ({v['count']} ops)")
    for (cat, nm), ps in sorted(s["ops"].items(), key=lambda kv: -kv[1])[:12]:
        print(f"  {ps/1e9/K:7.3f} ms/step  [{cat}] {nm[:76]}")
    print("---- copies and loop fusions ----")
    shown = 0
    for (cat, nm), ps in sorted(s["ops"].items(), key=lambda kv: -kv[1]):
        if cat in ("copy-done", "copy", "loop fusion", "data formatting"):
            print(f"  {ps/1e9/K:7.3f} ms/step  [{cat}] {nm[:76]}")
            shown += 1
            if shown > 25:
                break
    shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "vit_base")
