"""Profiler-based (tunnel-noise-immune) timing of the flash kernels.

Captures an xprof trace of K chained iterations and reads per-op DEVICE
time via utils/xprof.op_summary — the same method behind the round-3
roofline numbers. Reports ms/iter for our fwd, our fwd+bwd, and the
bundled jax kernel at identical shapes/blocks.
"""
import shutil
import sys
import tempfile

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, "/root/repo")
from ddp_practice_tpu.utils.xprof import op_summary

PEAK = 197e12
K = 32


def device_ms(fn, args, label):
    @jax.jit
    def run(q, k, v):
        def body(c, _):
            return fn(c, k, v), ()
        o, _ = lax.scan(body, q, None, length=K)
        return jnp.float32(o.astype(jnp.float32).sum())

    float(run(*args))  # compile + warm
    tmp = tempfile.mkdtemp(prefix=f"xp_{label}_")
    with jax.profiler.trace(tmp):
        float(run(*args))
    s = op_summary(tmp)
    shutil.rmtree(tmp, ignore_errors=True)
    total_ms = s["total_ps"] / 1e9 / K
    by_op = sorted(s["ops"].items(), key=lambda kv: -kv[1])[:6]
    detail = {nm: ps / 1e9 / K for (cat, nm), ps in by_op}
    return total_ms, detail


def main():
    from ddp_practice_tpu.ops.flash_attention import flash_attention_with_lse
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as jax_flash)

    bh, s, d = 96, 2048, 64
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (bh, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (bh, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (bh, s, d), jnp.bfloat16)

    def ours_fwd(q, k, v):
        o, _ = flash_attention_with_lse(q, k, v, causal=True)
        return o

    def ours_fwdbwd(q, k, v):
        f = lambda q, k, v: flash_attention_with_lse(
            q, k, v, causal=True)[0].sum()
        dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        # all three grads feed the carry so no kernel is dead-code-eliminated
        return lax.clamp(-1.0, (dq + dk + dv).astype(jnp.float32),
                         1.0).astype(q.dtype)

    bs = BlockSizes(
        block_q=512, block_k_major=1024, block_k=1024, block_b=1,
        block_q_major_dkv=512, block_k_major_dkv=1024,
        block_k_dkv=1024, block_q_dkv=512,
        block_k_major_dq=1024, block_k_dq=1024, block_q_dq=512,
    )

    def official_fwd(q, k, v):
        o = jax_flash(q.reshape(8, 12, s, d), k.reshape(8, 12, s, d),
                      v.reshape(8, 12, s, d), causal=True,
                      sm_scale=1.0 / d ** 0.5, block_sizes=bs)
        return o.reshape(bh, s, d)

    def official_fwdbwd(q, k, v):
        f = lambda q, k, v: official_fwd(q, k, v).sum()
        dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        return lax.clamp(-1.0, (dq + dk + dv).astype(jnp.float32),
                         1.0).astype(q.dtype)

    vis = 6 / 8
    fwd_fl = bh * 2 * 2.0 * s * s * d * vis
    bwd_fl = bh * 7 * 2.0 * s * s * d * vis

    for name, fn, fl in [
        ("ours fwd", ours_fwd, fwd_fl),
        ("ours fwd+bwd", ours_fwdbwd, fwd_fl + bwd_fl),
        ("jaxk fwd", official_fwd, fwd_fl),
        ("jaxk fwd+bwd", official_fwdbwd, fwd_fl + bwd_fl),
    ]:
        ms, detail = device_ms(fn, (q, k, v), name.replace(" ", "_"))
        tf = fl / (ms / 1e3) / 1e12
        print(f"{name:14s}: {ms:7.3f} ms/iter  executed {tf:6.1f} TF/s"
              f"  ({100 * tf * 1e12 / PEAK:.1f}% of bf16 peak)")
        for nm, m in detail.items():
            print(f"    {nm[:60]:60s} {m:7.3f} ms")


if __name__ == "__main__":
    main()
