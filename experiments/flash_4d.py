"""Does a 4D-grid flash kernel reading (b, s, h, d) directly (strided DMA)
beat the fold-transpose path? Times the model-boundary view: input is
(b, s, h*d) as produced by the qkv matmul, output must be (b, s, h*d)."""
import functools
import shutil
import sys
import tempfile

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from ddp_practice_tpu.ops.pallas_compat import tpu_compiler_params
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")
from ddp_practice_tpu.ops.flash_attention import (
    _fwd_kernel, _LANES, _kv_index_map)
from ddp_practice_tpu.utils.xprof import op_summary

K = 24


def fwd4d(q, k, v, *, causal=True, block_q=512, block_k=1024):
    """q/k/v: (b, s, h, d) — no transpose; grid (b, h, q-blocks, k-blocks)."""
    b, seq_q, h, d = q.shape
    seq_k = k.shape[1]
    sm_scale = 1.0 / d ** 0.5
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        causal=causal, seq_q=seq_q, seq_k=seq_k,
    )
    offset = seq_k - seq_q if causal else 0
    if causal:
        def kv_map(b_, h_, i, j):
            vis = (i * block_q + block_q - 1 + offset) >= (j * block_k)
            return (b_, lax.select(vis, j, 0), h_, 0)
    else:
        def kv_map(b_, h_, i, j):
            return (b_, j, h_, 0)

    # patch program ids: kernel uses program_id(1)=q-block, (2)=k-block;
    # in the 4D grid they are (2) and (3) — wrap the kernel.
    def kernel4(q_ref, k_ref, v_ref, o_ref, lse_ref, m, l, acc):
        # reuse the 3D kernel by shifting ids via closure: easiest is to
        # re-derive the same body with ids 2/3. Import-free inline:
        return _fwd_kernel_ids(q_ref, k_ref, v_ref, o_ref, lse_ref, m, l,
                               acc, sm_scale=sm_scale, block_q=block_q,
                               block_k=block_k, causal=causal, seq_q=seq_q,
                               seq_k=seq_k)

    out, lse = pl.pallas_call(
        kernel4,
        grid=(b, h, seq_q // block_q, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, None, d),
                         lambda b_, h_, i, j: (b_, i, h_, 0)),
            pl.BlockSpec((None, block_k, None, d), kv_map),
            pl.BlockSpec((None, block_k, None, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, None, d),
                         lambda b_, h_, i, j: (b_, i, h_, 0)),
            pl.BlockSpec((None, block_q, None, 1),
                         lambda b_, h_, i, j: (b_, i, h_, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, seq_q, h, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")
        ),
    )(q, k, v)
    return out


def _fwd_kernel_ids(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                    acc_scr, *, sm_scale, block_q, block_k, causal, seq_q,
                    seq_k):
    """_fwd_kernel with grid ids at (2, 3) instead of (1, 2)."""
    from ddp_practice_tpu.ops import flash_attention as fa

    qi = pl.program_id(2)
    kj = pl.program_id(3)
    n_k = pl.num_programs(3)
    offset = seq_k - seq_q if causal else 0
    d = v_ref.shape[-1]

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    visible = (
        (qi * block_q + block_q - 1 + offset) >= (kj * block_k)
        if causal else (kj >= 0)
    )

    @pl.when(visible)
    def _compute():
        q = (q_ref[:] * sm_scale).astype(q_ref.dtype)
        s = fa._dot_tb(q, k_ref[:])
        if causal:
            s = s + fa._causal_penalty(qi, kj, block_q, block_k, offset)
        m_prev = m_scr[:]
        l_prev = l_scr[:]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
        p = jnp.exp(s - fa._widen(m_next, block_k))
        alpha = jnp.exp(m_prev - m_next)
        l_corr = alpha * l_prev
        l_next = l_corr + jnp.sum(p, axis=1)[:, None]
        l_inv = jnp.where(l_next == 0.0, 1.0, 1.0 / l_next)
        m_scr[:] = m_next
        l_scr[:] = l_next
        pv = lax.dot_general(
            p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = (
            acc_scr[:] * fa._widen(l_corr * l_inv, d) + pv * fa._widen(l_inv, d)
        )

    @pl.when(kj == n_k - 1)
    def _finalize():
        o_ref[:] = acc_scr[:].astype(o_ref.dtype)
        l_col = l_scr[:, :1]
        lse_ref[:] = m_scr[:, :1] + jnp.log(jnp.maximum(l_col, 1e-30))


def device_ms(fn, args):
    @jax.jit
    def run(x, *rest):
        def body(c, _):
            return fn(c, *rest), ()
        o, _ = lax.scan(body, x, None, length=K)
        return jnp.float32(o.astype(jnp.float32).sum())

    float(run(*args))
    tmp = tempfile.mkdtemp(prefix="xp_4d_")
    with jax.profiler.trace(tmp):
        float(run(*args))
    s = op_summary(tmp)
    shutil.rmtree(tmp, ignore_errors=True)
    cats = {c: v["ps"] / 1e9 / K for c, v in s["categories"].items()}
    return s["total_ps"] / 1e9 / K, cats


def main():
    from ddp_practice_tpu.ops.flash_attention import flash_attention

    b, s, h, d = 8, 2048, 12, 64
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    # model boundary: flat (b, s, h*d) activations
    qf = jax.random.normal(kq, (b, s, h * d), jnp.bfloat16)
    kf = jax.random.normal(kk, (b, s, h * d), jnp.bfloat16)
    vf = jax.random.normal(kv, (b, s, h * d), jnp.bfloat16)

    def path_fold(qf, kf, vf):
        q = qf.reshape(b, s, h, d)
        k = kf.reshape(b, s, h, d)
        v = vf.reshape(b, s, h, d)
        o = flash_attention(q, k, v, causal=True)  # transposes inside
        return o.reshape(b, s, h * d)

    def path_4d(qf, kf, vf):
        q = qf.reshape(b, s, h, d)
        k = kf.reshape(b, s, h, d)
        v = vf.reshape(b, s, h, d)
        o = fwd4d(q, k, v, causal=True)
        return o.reshape(b, s, h * d)

    # numerics
    ref = path_fold(qf, kf, vf)
    got = path_4d(qf, kf, vf)
    err = float(jnp.max(jnp.abs(ref.astype(jnp.float32)
                                - got.astype(jnp.float32))))
    print(f"max abs diff 4d vs fold: {err:.2e}")

    for name, fn in [("fold+transpose", path_fold), ("4d-direct", path_4d)]:
        ms, cats = device_ms(fn, (qf, kf, vf))
        fmt = ", ".join(f"{c}: {v:.3f}" for c, v in sorted(
            cats.items(), key=lambda kv: -kv[1])[:4])
        print(f"{name:15s}: {ms:7.3f} ms/iter   [{fmt}]")


if __name__ == "__main__":
    main()
