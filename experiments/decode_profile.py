"""Profile the batched decode step (bs=8, lm_base) — per-op device time.
Identifies the KV-cache update copy the round-3 profile measured at ~47%
of the step (VERDICT item 2)."""
import shutil
import sys
import tempfile

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")


def main(batch=8, new_tokens=64):
    from ddp_practice_tpu.config import PrecisionPolicy
    from ddp_practice_tpu.inference import (
        cast_params_for_streaming, make_generate_fn)
    from ddp_practice_tpu.models import create_model
    from ddp_practice_tpu.utils.xprof import print_summary

    policy = PrecisionPolicy.from_name("bf16")
    model = create_model("lm_base", policy=policy, vocab_size=32768,
                         max_len=1024, pos_emb="rope")
    prompt = jnp.ones((batch, 128), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    params = cast_params_for_streaming(params)
    gen = jax.jit(make_generate_fn(model, max_new_tokens=new_tokens,
                                   temperature=1.0))
    key = jax.random.PRNGKey(1)
    toks = gen(params, prompt, key)
    toks.block_until_ready()
    _ = int(toks[0, -1])
    tmp = tempfile.mkdtemp(prefix="xp_dec_")
    with jax.profiler.trace(tmp):
        toks = gen(params, prompt, key)
        _ = int(toks[0, -1])
    print_summary(tmp, steps=new_tokens, top=18)
    shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--new", type=int, default=64)
    a = p.parse_args()
    main(a.batch, a.new)
