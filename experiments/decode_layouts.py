"""KV-cache layout shootout for batched decode (VERDICT item 2).

One lm_base-shaped layer (b=8, h=12, hd=64, L=1024), 64-step scan of
single-token decode bodies; per-op device time via xprof. Layouts:

  A_blhd  — current: cache (b, L, h, hd), DUS at (0, cur, 0, 0),
            attention_with_mask einsums (q broadcast to 8 rows).
  B_bhld  — cache (b, h, L, hd), DUS at (0, 0, cur, 0);
            scores "bhqd,bhld->bhql", pv "bhql,bhld->bhqd".
  C_bhdl  — seq-minor: cache (b, h, hd, L), DUS at (0, 0, 0, cur);
            scores "bhqd,bhdl->bhql", pv "bhql,bhdl->bhqd".

All three use an 8-row query broadcast (sublane width) so the dots hit
the MXU; the result row is sliced back out.
"""
import shutil
import sys
import tempfile

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, "/root/repo")
from ddp_practice_tpu.utils.xprof import op_summary

B, H, HD, L = 8, 12, 64, 1024
STEPS = 64
Q8 = 8


def attn_a(q, kc, vc, cur):
    """(b, L, h, hd) cache — the current attention_with_mask path."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q,
                        kc.astype(q.dtype),
                        preferred_element_type=jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.asarray(HD, jnp.float32))
    mask = (jnp.arange(L)[None, :] <= cur)[None, None]
    scores = jnp.where(mask, scores * scale, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), vc,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def attn_bh(q, kc, vc, cur, *, seq_minor):
    """(b, h, L, hd) or (b, h, hd, L) caches; q (b, q8, h, hd)."""
    qh = jnp.transpose(q, (0, 2, 1, 3))  # (b, h, q8, hd) — tiny
    if seq_minor:
        scores = jnp.einsum("bhqd,bhdl->bhql", qh, kc.astype(q.dtype),
                            preferred_element_type=jnp.float32)
    else:
        scores = jnp.einsum("bhqd,bhld->bhql", qh, kc.astype(q.dtype),
                            preferred_element_type=jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.asarray(HD, jnp.float32))
    mask = (jnp.arange(L)[None, :] <= cur)[None, None]
    scores = jnp.where(mask, scores * scale, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if seq_minor:
        out = jnp.einsum("bhql,bhdl->bhqd", probs, vc,
                         preferred_element_type=jnp.float32)
    else:
        out = jnp.einsum("bhql,bhld->bhqd", probs, vc,
                         preferred_element_type=jnp.float32)
    return jnp.transpose(out.astype(q.dtype), (0, 2, 1, 3))


def body_a(carry, _):
    kc, vc, x, cur = carry
    k = x.reshape(B, 1, H, HD)
    kc = lax.dynamic_update_slice(kc, k, (0, cur, 0, 0))
    vc = lax.dynamic_update_slice(vc, k, (0, cur, 0, 0))
    q8 = jnp.broadcast_to(x.reshape(B, 1, H, HD), (B, Q8, H, HD))
    out = attn_a(q8, kc, vc, cur)[:, :1]
    return (kc, vc, out.reshape(B, H * HD), cur + 1), ()


def body_b(carry, _):
    kc, vc, x, cur = carry
    k = jnp.transpose(x.reshape(B, 1, H, HD), (0, 2, 1, 3))  # (b,h,1,hd)
    kc = lax.dynamic_update_slice(kc, k, (0, 0, cur, 0))
    vc = lax.dynamic_update_slice(vc, k, (0, 0, cur, 0))
    q8 = jnp.broadcast_to(x.reshape(B, 1, H, HD), (B, Q8, H, HD))
    out = attn_bh(q8, kc, vc, cur, seq_minor=False)[:, :1]
    return (kc, vc, out.reshape(B, H * HD), cur + 1), ()


def body_c(carry, _):
    kc, vc, x, cur = carry
    k = jnp.transpose(x.reshape(B, 1, H, HD), (0, 2, 3, 1))  # (b,h,hd,1)
    kc = lax.dynamic_update_slice(kc, k, (0, 0, 0, cur))
    vc = lax.dynamic_update_slice(vc, k, (0, 0, 0, cur))
    q8 = jnp.broadcast_to(x.reshape(B, 1, H, HD), (B, Q8, H, HD))
    out = attn_bh(q8, kc, vc, cur, seq_minor=True)[:, :1]
    return (kc, vc, out.reshape(B, H * HD), cur + 1), ()


def run_case(name, body, cache_shape):
    @jax.jit
    def loop(x):
        kc = jnp.zeros(cache_shape, jnp.bfloat16)
        vc = jnp.zeros(cache_shape, jnp.bfloat16)
        carry, _ = lax.scan(body, (kc, vc, x, jnp.int32(0)), None,
                            length=STEPS)
        return jnp.float32(carry[2].astype(jnp.float32).sum())

    x = jax.random.normal(jax.random.PRNGKey(0), (B, H * HD), jnp.bfloat16)
    float(loop(x))
    tmp = tempfile.mkdtemp(prefix="xp_lay_")
    with jax.profiler.trace(tmp):
        float(loop(x))
    s = op_summary(tmp)
    shutil.rmtree(tmp, ignore_errors=True)
    total = s["total_ps"] / 1e9 / STEPS
    dus = s["categories"].get("dynamic-update-slice", {"ps": 0})["ps"] / 1e9 / STEPS
    print(f"{name}: {total*1e3:7.1f} us/step total, DUS {dus*1e3:6.1f} us "
          f"({100*dus/max(total,1e-9):.0f}%)")


if __name__ == "__main__":
    run_case("A_blhd (current)", body_a, (B, L, H, HD))
    run_case("B_bhld          ", body_b, (B, H, L, HD))
    run_case("C_bhdl seq-minor", body_c, (B, H, HD, L))
