"""Benchmark: MNIST ConvNet training throughput, images/sec/chip.

The BASELINE.json north-star metric. The reference's published number is
22.72 s wall-clock for 3 epochs x 60k images + eval on one (unnamed) GPU
(README.md:201) => ~7,923 images/sec; `vs_baseline` is the ratio of this
run's steady-state images/sec/chip to that.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Config mirrors the reference DDP variant per-replica batch 32 with the
TPU-native AMP equivalent (bf16); flags allow fp32/other batch sizes.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


REFERENCE_IMAGES_PER_SEC = 60000 * 3 / 22.72  # README.md:201 (incl. eval)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench")
    p.add_argument("--batch_size", type=int, default=32, help="per replica")
    p.add_argument("--precision", default="bf16", choices=["fp32", "bf16"])
    p.add_argument("--model", default="convnet")
    p.add_argument("--dataset", default="mnist")
    p.add_argument("--warmup", type=int, default=64)
    p.add_argument("--steps", type=int, default=640)
    p.add_argument("--steps_per_call", type=int, default=32,
                   help="K optimizer steps per jitted call (1 = off)")
    args = p.parse_args(argv)

    import jax

    from ddp_practice_tpu.config import MeshConfig, TrainConfig
    from ddp_practice_tpu.data.loader import prefetch_chunked, prefetch_to_device
    from ddp_practice_tpu.train.loop import Trainer

    k = max(1, args.steps_per_call)
    cfg = TrainConfig(
        model=args.model,
        dataset=args.dataset,
        batch_size=args.batch_size,
        precision=args.precision,
        log_every_steps=0,
        steps_per_call=k,
        mesh=MeshConfig(data=-1),
    )
    trainer = Trainer(cfg)
    n_chips = jax.device_count()

    def batches():
        """Endless stream of device batches: stacked chunks when k > 1."""
        epoch = 0
        while True:
            trainer.train_loader.set_epoch(epoch)
            if k > 1:
                it = prefetch_chunked(
                    iter(trainer.train_loader), k,
                    trainer.batch_shardings, trainer.stacked_shardings, size=2,
                )
                for tag, b in it:
                    if tag == "chunk":  # drop the sub-k epoch tail
                        yield b
            else:
                yield from prefetch_to_device(
                    iter(trainer.train_loader), trainer.batch_shardings, size=2
                )
            epoch += 1

    step_fn = trainer.chunk_step if k > 1 else trainer.train_step
    n_calls = -(-args.steps // k)

    it = batches()
    try:
        state = trainer.state
        for _ in range(max(args.warmup // k, 2)):
            state, metrics = step_fn(state, next(it))
        jax.block_until_ready(state.params)

        t0 = time.perf_counter()
        for _ in range(n_calls):
            state, metrics = step_fn(state, next(it))
        jax.block_until_ready(state.params)
        dt = time.perf_counter() - t0
    finally:
        it.close()  # stop the prefetch producer thread before interpreter exit

    ips = n_calls * k * trainer.global_batch / dt
    ips_per_chip = ips / n_chips
    print(
        json.dumps(
            {
                "metric": f"{args.model}/{args.dataset} train throughput "
                          f"(bs={args.batch_size}/replica, {args.precision}, "
                          f"{n_chips} chip(s))",
                "value": round(ips_per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(ips_per_chip / REFERENCE_IMAGES_PER_SEC, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
