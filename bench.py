"""Benchmark: steady-state training throughput and MFU, one JSON line.

Headline: ViT-Base (the MXU-bound flagship transformer) training
images/sec/chip with computed MFU against the chip's bf16 peak. The final
stdout line is a COMPACT driver-parseable record (metric/value/unit/
vs_baseline + headline MFU only); the full per-model suite is written to
BENCHMARKS.json next to this file. Companion entries there: ViT-Tiny
(HBM-bound at d=192 — see BENCHMARKS.md), the ConvNet/MNIST parity model
(the BASELINE.json north-star metric, with `vs_baseline` = ratio to the
reference's ~7,923 images/sec implied by README.md:201), ResNet-18,
ResNet-50 at ImageNet shape, and the LM train/decode entries.

Methodology — device-resident uint8 data pool, on-device gather+normalize,
K steps per dispatch, timing fenced by a scalar host readback — is
documented in BENCHMARKS.md. End-to-end wall-clock numbers with the real
input pipeline live in PARITY.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import traceback


REFERENCE_IMAGES_PER_SEC = 60000 * 3 / 22.72  # README.md:201 (incl. eval)

# (name, kwargs) — per-model saturating configs for one chip
_SUITE = {
    # the DEFAULT vit_tiny path: since round 5 fused="auto" selects the
    # Pallas encoder-layer kernels (ops/fused_encoder.py) on a single
    # TPU chip without flags — this entry records what a user gets
    "vit_tiny": dict(
        image_shape=(32, 32, 3), batch_size=1024, steps_per_call=32, calls=8,
    ),
    # the per-op XLA pipeline, kept as the documented companion number
    # (BENCHMARKS.md "Why ViT-Tiny sat at ~17%" — the HBM-bound small-d
    # regime the fused kernels fix)
    "vit_tiny_unfused": dict(
        model="vit_tiny", image_shape=(32, 32, 3), batch_size=1024,
        steps_per_call=32, calls=8, model_kwargs={"fused": False},
    ),
    # FORCED fused=True (fails loudly if the kernel can't run): on a
    # single chip identical to "vit_tiny" above, but auto falls back to
    # per-op on multichip hosts (EncoderBlock._auto_fuse's device gate) —
    # this entry keeps the fused measurement in the default suite there.
    "vit_tiny_fused": dict(
        model="vit_tiny", image_shape=(32, 32, 3), batch_size=1024,
        steps_per_call=32, calls=8, model_kwargs={"fused": True},
    ),
    "vit_base": dict(
        # bs swept 96..512 on v5e (2026-07-30): 192 is the plateau top —
        # 54.9% MFU vs 48.0% at the earlier 256 default; throughput falls
        # ~19% by bs 512 (activation traffic, not MXU, sets the ceiling).
        # calls=24: the chip clocks up under SUSTAINED load (the ramp
        # the ConvNet entry quantifies) — at 6 calls the 1.4 s
        # half-windows read ~5% low
        image_shape=(32, 32, 3), batch_size=192, steps_per_call=8,
        calls=24,
    ),
    # the vs_baseline denominator — measured over LONG windows: at
    # ~0.4 ms/step the old 32-step calls were dispatch-amortization-bound
    # and the recorded rate swung 62-91k img/s run to run (round-3
    # verdict item 7). 512 steps/call fixed that; the round-4 second
    # pass then found the rate RAMPS with sustained load (half-window
    # rates: 219k at 8 calls -> 253k at 16 -> 284k at 32, where the two
    # fenced half-windows finally agree within ~1% — short windows
    # measure a cold-clock chip). 32 calls x 512 steps = ~2.4 s per
    # half-window; repeats land 278-284k img/s.
    "convnet": dict(
        image_shape=(28, 28, 1), batch_size=32, steps_per_call=512,
        calls=32, warmup_calls=4, pool_size=4096,
    ),
    # resnet windows lengthened for the same clock-ramp reason as
    # vit_base/convnet (short windows read a cold chip ~5-8% low)
    "resnet18": dict(
        image_shape=(32, 32, 3), batch_size=512, steps_per_call=16,
        calls=16,
    ),
    "resnet50": dict(
        image_shape=(224, 224, 3), num_classes=1000, batch_size=128,
        steps_per_call=8, calls=12, pool_size=512,
    ),
    # long-context LM entries (kind="lm" -> bench_lm_train: tokens/sec +
    # MFU; causal flash attention). lm_long runs in the default list; the
    # longer lengths are opt-in: `--models lm_8k` / `--models lm_16k`.
    "lm_long": dict(
        # K=8 steps/dispatch: at ~140 ms/step the tunnel's dispatch+
        # readback overhead is ~7 ms/step at K=4 and halves at K=8
        # (measured 45.99 vs 46.29% MFU; bs swept 8/16/32 -> 46.7/45.7/
        # 43.5% — activation HBM traffic favors the small batch)
        kind="lm", seq_len=2048, batch_size=8, steps_per_call=8, calls=6,
    ),
    # MoE LM at lm_base dims, experts every other block (GShard layout),
    # under EXPERT-CHOICE routing (ops/moe.py expert_choice_gating) —
    # the TPU-first router: experts pick tokens, so every buffer slot
    # fills — zero drops and zero capacity padding BY CONSTRUCTION
    # (cf 1.0: executed expert FLOPs == active FLOPs, vs the 1.5x a
    # token-choice capacity factor executes). Measured round 5:
    # 44.3% MFU vs 37.7% token-choice — the padding was the whole
    # remaining MoE-dense gap (the round-5 BENCHMARKS.md MoE section
    # records the full dispatch-glue shootout that led here). Groups
    # of 256 strided tokens bound both the dispatch einsum cost and
    # the EC routing-competition scope (group 128/512 measured 42.0/
    # 41.4%).
    "lm_moe": dict(
        kind="lm", model="lm_moe", seq_len=2048, batch_size=8,
        steps_per_call=4, calls=4, warmup_calls=10, data="corpus",
        model_kwargs={
            "hidden_dim": 768, "depth": 12, "num_heads": 12,
            "mlp_dim": 3072, "moe_every": 2, "num_experts": 8,
            "moe_group_size": 256, "capacity_factor": 1.0,
            "moe_router": "expert_choice",
        },
    ),
    # the token-choice (GShard/Switch top-k) record: tokens/sec + MFU
    # (active-FLOPs accounting) + router drop rate. warmup 10 calls
    # (40 steps) + the synthetic Markov corpus so the recorded router
    # health is the WARM equilibrium of the balancing machinery (fixed
    # Switch aux + DeepSeek-style selection bias), not init-state
    # garbage — the round-3 entry recorded an untrained router's
    # drop=0.30 on uniform-random tokens (round-3 verdict item 3).
    # Routing groups of 256 strided-interleaved tokens at capacity 1.5
    # (round-4 sweep): the dispatch/combine einsums are O(group_size)
    # per token, so 2048 -> 256 cuts them ~8x, and the interleave
    # decorrelates per-group demand enough that cf 1.5 drops LESS
    # (1.1%) than whole-sequence cf 2.0 did (1.4%). Kept in the suite:
    # token-choice is the strictly-causal training scheme (see the EC
    # caveat in ops/moe.py) and the multichip expert-parallel path's
    # semantics.
    "lm_moe_tc": dict(
        kind="lm", model="lm_moe", seq_len=2048, batch_size=8,
        steps_per_call=4, calls=4, warmup_calls=10, data="corpus",
        model_kwargs={
            "hidden_dim": 768, "depth": 12, "num_heads": 12,
            "mlp_dim": 3072, "moe_every": 2, "num_experts": 8,
            "moe_group_size": 256, "capacity_factor": 1.5,
        },
    ),
    # short-seq decoder LM through the fused Pallas encoder-layer kernels
    # (round 4: ops/fused_encoder.py grew causal masking) — the d=256
    # HBM-bound regime's fix applied to the LM family. heads=4 keeps
    # head_dim 64 (the kernel's 64-aligned column-slice contract);
    # attn_impl stays xla (the whole layer IS the kernel). Companion
    # unfused number in BENCHMARKS.md: 1.70x.
    "lm_tiny_fused": dict(
        kind="lm", model="lm_tiny", seq_len=256, batch_size=256,
        steps_per_call=16, calls=12, warmup_calls=4, attn_impl="xla",
        data="corpus",
        model_kwargs={"num_heads": 4, "fused": True},
    ),
    "lm_8k": dict(
        kind="lm", seq_len=8192, batch_size=2, steps_per_call=2, calls=3,
    ),
    "lm_16k": dict(
        kind="lm", seq_len=16384, batch_size=1, steps_per_call=2, calls=3,
    ),
    "lm_32k": dict(
        kind="lm", seq_len=32768, batch_size=1, steps_per_call=1, calls=2,
        model_kwargs={"remat": True},
    ),
    # autoregressive generation (KV-cache decode, inference.py): tokens/sec
    # + model-bandwidth utilization — decode re-reads all params per token,
    # so the roofline is HBM, not the MXU. bs=1 is the single-stream MBU
    # flagship (params-streaming bound); bs=8 trades MBU for batch rate.
    # Params stream as bf16 (inference needs no fp32 masters).
    "lm_decode": dict(
        kind="decode", prompt_len=128, max_new_tokens=512, batch_size=8,
        calls=3,
    ),
    "lm_decode_bs1": dict(
        kind="decode", prompt_len=128, max_new_tokens=512, batch_size=1,
        calls=3,
    ),
    # longer-context batched decode with the INT8 KV cache
    # (models/vit.py kv_cache_dtype="int8" + the quantized packed
    # kernel): at L=1024 the bf16 cache read is ~1.8x the param stream,
    # and int8 measured +17.5% tokens/s over bf16 (0.544 vs 0.663
    # ms/step; the crossover is L~768 — below it the scale-buffer
    # traffic eats the saving, so the short entries stay bf16).
    "lm_decode_1k": dict(
        kind="decode", prompt_len=256, max_new_tokens=768, batch_size=8,
        calls=3, kv_cache="int8",
    ),
}


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench")
    p.add_argument("--models",
                   default="vit_base,vit_tiny,vit_tiny_unfused,"
                           "vit_tiny_fused,convnet,"
                           "resnet18,resnet50,lm_long,lm_moe,lm_moe_tc,"
                           "lm_tiny_fused,lm_decode,lm_decode_bs1,"
                           "lm_decode_1k",
                   help="comma-separated; first successful is the headline")
    p.add_argument("--precision", default="bf16", choices=["fp32", "bf16"])
    p.add_argument("--batch_size", type=int, default=0, help="override")
    p.add_argument("--steps_per_call", type=int, default=0, help="override")
    p.add_argument("--calls", type=int, default=0, help="override")
    args = p.parse_args(argv)

    from ddp_practice_tpu.benchmarks import (
        bench_lm_decode,
        bench_lm_train,
        bench_train,
    )

    results = []
    errors = []
    names = [m.strip() for m in args.models.split(",") if m.strip()]
    unknown = [n for n in names if n not in _SUITE]
    if unknown:
        p.error(f"no bench config for {unknown}; known: {sorted(_SUITE)}")
    for name in names:
        kw = dict(_SUITE[name])
        kind = kw.pop("kind", "image")
        kw["precision"] = args.precision
        if args.batch_size:
            if name.endswith("_bs1"):
                # the entry's identity pins its batch size; an override
                # would record a wrong number under the bs1 name
                print(f"[bench] --batch_size ignored for {name}",
                      file=sys.stderr)
            else:
                kw["batch_size"] = args.batch_size
        if args.steps_per_call:
            kw["steps_per_call"] = args.steps_per_call
        if args.calls:
            kw["calls"] = args.calls
        try:
            if kind == "lm":
                r = bench_lm_train(kw.pop("model", "lm_base"), **kw)
                r["model"] = name
                results.append(r)
            elif kind == "decode":
                r = bench_lm_decode("lm_base", **kw)
                r["model"] = name
                results.append(r)
            else:
                r = bench_train(kw.pop("model", name), **kw)
                r["model"] = name
                results.append(r)
        except Exception:  # noqa: BLE001 — a failed model must not kill the line
            errors.append({"model": name, "error": traceback.format_exc(limit=3)})

    if not results:
        # deliberately do NOT touch BENCHMARKS.json here: a transient
        # all-models failure must not clobber the last good recorded suite
        for e in errors:
            print(f"[bench] {e['model']} failed:\n{e['error']}",
                  file=sys.stderr)
        print(json.dumps({
            "metric": "bench failed", "value": 0.0, "unit": "images/sec/chip",
            "vs_baseline": 0.0, "n_errors": len(errors),
        }))
        return 1

    head = results[0]
    head_rate = head.get(
        "images_per_sec_per_chip", head.get("tokens_per_sec_per_chip", 0.0)
    )
    head_unit = (
        "images/sec/chip" if "images_per_sec_per_chip" in head
        else "tokens/sec/chip"
    )
    convnet = next((r for r in results if r["model"] == "convnet"), None)
    if convnet:
        vs_baseline = round(
            convnet["images_per_sec_per_chip"] / REFERENCE_IMAGES_PER_SEC, 3
        )
        vs_note = (
            "ratio of the ConvNet/MNIST companion entry (results) to the "
            "reference's ~7,923 img/s (README.md:201); the reference "
            "publishes no transformer numbers"
        )
    else:
        vs_baseline = round(head_rate / REFERENCE_IMAGES_PER_SEC, 3)
        vs_note = (
            f"CROSS-MODEL ratio: {head['model']} {head_unit} over the "
            "reference's ConvNet/MNIST ~7,923 img/s (README.md:201) — no "
            "convnet entry ran in this invocation; rerun with "
            "--models convnet,... for the like-for-like number"
        )
    head_mode = "decode" if head.get("mode") == "decode" else "train"
    line = {
        "metric": (
            f"{head['model']} {head_mode} throughput (bs={head['batch_size']}, "
            f"{head['precision']}, {head['n_chips']} chip(s), "
            f"{head['device_kind']})"
        ),
        "value": head_rate,
        "unit": head_unit,
        "vs_baseline": vs_baseline,
    }
    if "mfu_pct" in head:
        line["mfu_pct"] = head["mfu_pct"]
        line["tflops_per_chip"] = head["tflops_per_chip"]
    if "mbu_pct" in head:
        line["mbu_pct"] = head["mbu_pct"]
    if errors:
        line["n_errors"] = len(errors)

    # Full suite (every model record, the vs_baseline provenance note, and
    # any tracebacks) goes to a file; the driver's tail capture only needs
    # the compact line above. BENCH_r02 taught us the hard way: a several-KB
    # stdout line gets truncated mid-record and parses as null.
    _write_suite({
        "headline": head,
        "results": results,
        "vs_baseline": vs_baseline,
        "vs_baseline_note": vs_note,
        "errors": errors,
    }, partial=(
        args.models != p.get_default("models")
        or args.precision != p.get_default("precision")
        or bool(args.batch_size or args.steps_per_call or args.calls)
    ))
    print(json.dumps(line))
    return 0


def _write_suite(suite: dict, *, partial: bool = False) -> None:
    """Dump the full suite next to this file; never kill the stdout line.

    Partial invocations (a custom --models subset) write to
    BENCHMARKS.partial.json so they cannot clobber the recorded
    default-suite results that BENCHMARKS.md cites.
    """
    name = "BENCHMARKS.partial.json" if partial else "BENCHMARKS.json"
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    try:
        with open(path, "w") as f:
            json.dump(suite, f, indent=1)
        print(f"full suite -> {path}", file=sys.stderr)
    except OSError as e:  # read-only checkout / full disk: line still prints
        print(f"could not write {path}: {e}", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
