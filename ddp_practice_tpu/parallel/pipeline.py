"""Pipeline parallelism: GPipe microbatch schedule over the 'pipe' mesh axis.

Absent from the reference (single forward per step, no stage partitioning —
SURVEY §2.3 "Pipeline parallel — No"). TPU-first design: no per-stage
processes or send/recv threads (the GPU idiom). Instead the whole pipeline
is ONE jitted SPMD program:

- the block stack's parameters carry a leading stage dimension sharded over
  the 'pipe' mesh axis — each device holds depth/P blocks;
- a `lax.scan` over M + P - 1 ticks runs the GPipe schedule: stage 0
  ingests a fresh microbatch each tick, every stage applies its local
  blocks, and activations hop stage→stage via `lax.ppermute` (one ICI
  neighbor exchange per tick);
- the last stage's emitted microbatches are re-broadcast with a masked
  `psum`, so downstream (GSPMD) code sees the output replicated over
  'pipe'.

The backward pass is just XLA differentiating the scan: reversed ppermutes,
exactly the 1F1B-style reverse hops, with the latency-hiding scheduler
overlapping compute and ICI traffic. Composes with the 'data' axis (batch
dim stays sharded over 'data' inside the shard_map).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ddp_practice_tpu.config import MeshConfig
from ddp_practice_tpu.parallel.ring import get_current_mesh
from ddp_practice_tpu.parallel.compat import shard_map


def pipeline_apply(
    block_fn: Callable,
    stage_params,
    x: jnp.ndarray,
    *,
    num_microbatches: int,
    axis_name: str = MeshConfig.AXIS_PIPE,
    mesh=None,
    remat: bool = True,
):
    """Run `x` through a stage-sharded block stack with a GPipe schedule.

    block_fn(stage_params_local, x_mb) -> y_mb applies ONE stage's blocks
    (leading dim of each `stage_params` leaf is the global stage count;
    locally each device sees its own slice). x: (batch, ...) with batch
    sharded over 'data'; output has the same shape as x (residual-stack
    contract). num_microbatches must divide the per-data-shard batch.
    """
    mesh = mesh or get_current_mesh()
    if mesh is None:
        raise ValueError(
            "pipeline_apply needs a mesh (set via parallel.ring.set_current_mesh)"
        )
    data_spec = P(MeshConfig.AXIS_DATA)  # batch dim over 'data', repl. over 'pipe'
    param_spec = jax.tree.map(lambda _: P(axis_name), stage_params)
    # manual ONLY over 'pipe' (stage hops) and 'data' (microbatch split):
    # every other mesh axis stays GSPMD-automatic inside the stage body, so
    # tensor-parallel parameter shardings (sharding_rules._vit_pipe_rule)
    # propagate into the per-stage matmuls and XLA inserts the Megatron
    # all-reduces over 'tensor' there — TP x PP without hand collectives
    fn = shard_map(
        functools.partial(
            _pipeline_local,
            block_fn=block_fn,
            num_mb=num_microbatches,
            axis_name=axis_name,
            remat=remat,
        ),
        mesh=mesh,
        in_specs=(param_spec, data_spec),
        out_specs=data_spec,
        axis_names=frozenset({axis_name, MeshConfig.AXIS_DATA}),
        check_vma=False,
    )
    # Boundary values stay fp32: XLA 0.9 CHECK-fails ("Invalid binary
    # instruction opcode copy") building any sub-fp32 psum over the manual
    # axes of a PARTIAL-manual shard_map — including the implicit psums
    # grad-transpose inserts for operands replicated over a manual axis
    # (activations are replicated over 'pipe', params over 'data'). Params
    # are already fp32 under the bf16 policy; activations are cast here and
    # per-tick (_pipeline_local), while block compute stays in the model's
    # dtype. Cost: ppermute hops carry fp32 — 2x ICI bytes on one
    # activation tensor per tick.
    in_dtype = x.dtype
    out = jax.jit(fn)(stage_params, x.astype(jnp.float32))
    # the scan-over-ticks body can't be evaluated eagerly inside shard_map;
    # jit is a no-op when already under an outer jit trace
    return out.astype(in_dtype)


def _pipeline_local(stage_params, x, *, block_fn, num_mb, axis_name, remat):
    # local param leaves are (1, ...) — this device's single stage slice
    params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), stage_params)
    n_stages = lax.psum(1, axis_name)  # trace-time constant
    idx = lax.axis_index(axis_name)
    batch = x.shape[0]
    if batch % num_mb != 0:
        raise ValueError(
            f"per-shard batch {batch} not divisible by microbatches {num_mb}"
        )
    mb = batch // num_mb
    xs = x.reshape((num_mb, mb) + x.shape[1:])

    apply_stage = jax.checkpoint(block_fn) if remat else block_fn
    # stage i sends to stage i+1; the wrap-around link carries garbage that
    # stage 0 immediately overwrites with the next fresh microbatch
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        t_in = jnp.clip(t, 0, num_mb - 1)
        inp = jnp.where(idx == 0, xs[t_in], state)
        # carry stays in the (fp32) boundary dtype — see pipeline_apply —
        # while the block computes in the model's own dtype
        y = apply_stage(params, inp).astype(x.dtype)
        t_out = t - (n_stages - 1)
        emit = jnp.logical_and(idx == n_stages - 1, t_out >= 0)
        t_out = jnp.clip(t_out, 0, num_mb - 1)
        cur = lax.dynamic_index_in_dim(outputs, t_out, axis=0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(emit, y, cur), t_out, 0
        )
        state = lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    state0 = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    out0 = jnp.zeros_like(xs)
    (_, outputs), _ = lax.scan(
        tick, (state0, out0), jnp.arange(num_mb + n_stages - 1)
    )
    # only the last stage holds real outputs; masked psum replicates them
    # over 'pipe' so downstream GSPMD code is stage-agnostic. The psum runs
    # in fp32: XLA (0.9 CPU backend) CHECK-fails building a sub-fp32
    # all-reduce when the shard_map is manual over a subset of mesh axes
    # ("Invalid binary instruction opcode copy"), and the upcast is free
    # here (one masked tensor, bandwidth-bound either way).
    masked = jnp.where(idx == n_stages - 1, outputs, jnp.zeros_like(outputs))
    outputs = lax.psum(masked.astype(jnp.float32), axis_name).astype(x.dtype)
    return outputs.reshape((batch,) + x.shape[1:])


def stack_stages(per_block_params, n_stages: int):
    """Reshape a depth-stacked params tree (leading dim = depth) into a
    stage-stacked tree (leading dim = n_stages, second dim = depth/n_stages)
    suitable for `pipeline_apply` with a block_fn that scans its local
    blocks."""

    def reshape(leaf):
        depth = leaf.shape[0]
        if depth % n_stages != 0:
            raise ValueError(f"depth {depth} not divisible by {n_stages} stages")
        return leaf.reshape((n_stages, depth // n_stages) + leaf.shape[1:])

    return jax.tree.map(reshape, per_block_params)
