"""Ulysses (DeepSpeed-style) sequence parallelism: all-to-all head scatter.

Second sequence-parallel scheme next to `parallel.ring` (absent from the
reference, which has no sequence axis at all — SURVEY §5.7). Where ring
attention keeps queries local and rotates K/V blocks around the 'seq' mesh
axis, Ulysses re-shards with two all-to-alls:

    (batch, seq/N, heads, d) --all_to_all--> (batch, seq, heads/N, d)
      ... dense attention over the FULL sequence per (fewer) heads ...
    (batch, seq, heads/N, d) --all_to_all--> (batch, seq/N, heads, d)

Attention itself is then a plain fused softmax-attention over the whole
sequence — maximally MXU-friendly — at the cost of two all-to-alls over ICI
instead of ring ppermutes. Preferable when heads >> seq-axis size and the
sequence fits in HBM once gathered; ring wins for extreme lengths.

Requires local heads divisible by the 'seq' axis size (heads are already
divided by the 'tensor' axis under TP, so: heads % (tp * sp) == 0).
"""

from __future__ import annotations

import functools

import jax
from jax import lax

from ddp_practice_tpu.parallel.compat import shard_map
from ddp_practice_tpu.parallel.ring import (
    _axis_bound,
    _island_mesh_and_spec,
    get_current_mesh,
)


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = False,
                      mesh=None, impl: str = "xla"):
    """All-to-all sequence-parallel attention; same signature as ring.

    `impl` picks the local full-sequence attention after the head scatter:
    'xla' (fused dense) or 'flash' (the Pallas tiled kernel — O(seq)
    memory over the gathered sequence)."""
    if _axis_bound(axis_name):
        return _ulysses_local(
            q, k, v, axis_name=axis_name, causal=causal, impl=impl
        )
    mesh = mesh or get_current_mesh()
    if mesh is None:
        raise ValueError(
            "ulysses_attention outside shard_map needs a mesh "
            "(set via parallel.ring.set_current_mesh)"
        )
    mesh, spec = _island_mesh_and_spec(mesh, axis_name)
    fn = shard_map(
        functools.partial(
            _ulysses_local, axis_name=axis_name, causal=causal, impl=impl
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, impl: str = "xla"):
    from ddp_practice_tpu.ops.attention import _attention

    axis_size = lax.psum(1, axis_name)
    heads = q.shape[2]
    if heads % axis_size != 0:
        raise ValueError(
            f"ulysses needs local heads ({heads}) divisible by "
            f"'{axis_name}' axis size ({axis_size})"
        )

    def gather_seq_scatter_heads(x):
        # (b, s/N, h, d) -> (b, s, h/N, d)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def scatter_seq_gather_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qg = gather_seq_scatter_heads(q)
    kg = gather_seq_scatter_heads(k)
    vg = gather_seq_scatter_heads(v)
    if impl == "flash":
        from ddp_practice_tpu.ops.flash_attention import flash_attention

        out = flash_attention(qg, kg, vg, causal=causal)
    elif impl == "xla":
        out = _attention(qg, kg, vg, causal=causal)
    else:
        raise ValueError(f"unknown attention impl {impl!r} (want 'xla'|'flash')")
    return scatter_seq_gather_heads(out)
