"""Distributed runtime: mesh, multi-host init, sharding rules, collectives.

This package is the NCCL/DDP/torchrun replacement (SURVEY §2.2, §5.8):

- `dist.initialize`      ≈ init_process_group(nccl, env://) (ddp_main.py:69-73)
- `mesh.build_mesh`      ≈ rank/world bookkeeping — the mesh IS the backend
- GSPMD sharding (jit + NamedSharding) ≈ the DDP reducer's gradient
  all-reduce, lowered by XLA onto ICI/DCN
- `ring.ring_attention`  — sequence/context parallelism (absent from the
  reference; first-class here)
- `sharding_rules`       — tensor-parallel parameter PartitionSpecs
"""

from ddp_practice_tpu.parallel.mesh import (
    build_mesh,
    batch_sharding,
    replicated,
    shard_state,
)
from ddp_practice_tpu.parallel.dist import (
    initialize,
    is_main_process,
    process_count,
    process_index,
)
from ddp_practice_tpu.parallel.sharding_rules import param_sharding_rules
from ddp_practice_tpu.parallel.fsdp import fsdp_rules

__all__ = [
    "build_mesh",
    "batch_sharding",
    "replicated",
    "shard_state",
    "initialize",
    "is_main_process",
    "process_count",
    "process_index",
    "param_sharding_rules",
    "fsdp_rules",
]
