"""Multi-host runtime initialization.

Replaces the reference's process-group bootstrap: MASTER_ADDR/MASTER_PORT
env wiring + `init_process_group(backend="nccl", init_method="env://")`
(ddp_main.py:60-73) and torchrun's env contract
(ddp_main_torchrun.py:102-104). On TPU there is one process per *host*
(not per chip); `jax.distributed.initialize` performs the rendezvous and
after it `jax.devices()` spans the whole slice. No hardcoded port
(the reference pins 19198, ddp_main.py:62 — SURVEY §2.5 flags it).
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

log = logging.getLogger(__name__)

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Idempotent multi-host init.

    With no arguments, relies on the environment (TPU pod metadata or
    JAX_COORDINATOR_ADDRESS et al.); single-process runs skip rendezvous
    entirely — exactly like running origin_main.py without DDP.
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and num_processes in (None, 1):
        # Single-host: nothing to rendezvous; jax.devices() is local.
        _initialized = True
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log.info(
        "distributed initialized: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(), jax.device_count(),
    )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_main_process() -> bool:
    """The rank-0 gate for side effects (prints, checkpoint writes) —
    reference: ddp_main.py:158-169."""
    return jax.process_index() == 0
