"""jax API compatibility: shard_map across jax versions.

`jax.shard_map` (with the `check_vma` kwarg) is the stable spelling on
current jax; the image this repo targets may ship an older jax where it
only exists as `jax.experimental.shard_map.shard_map` (kwarg
`check_rep`). Every internal call site imports the symbol from here so
the version split lives in exactly one place.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pre-stable jax: experimental module, check_rep/auto spellings
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  axis_names=None):
        # new API names the MANUAL axes (axis_names, default all); the
        # old API names the complement (auto = axes left to GSPMD)
        kw = {}
        if axis_names is not None:
            kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, **kw,
        )
