"""1F1B pipeline schedule: memory-bounded training over the 'pipe' axis.

The GPipe path (parallel/pipeline.py) runs all forwards as one scan and
lets XLA differentiate it — simple, but the scan transpose stashes one
boundary activation per tick, so training memory grows O(M + P) with the
microbatch count M. This module is the memory-bounded alternative the
scale story needs (reference has no pipeline at all — SURVEY §2.3): the
backward is NOT autodiff-of-scan; each backward microbatch runs as an
explicit `jax.vjp` inside the schedule, so the only cross-tick activation
state is a ring stash of the last 2P-1 stage INPUTS — O(P), independent
of M. Double the microbatches and GPipe's activation memory doubles;
this schedule's stays put.

Schedule ("eager 1F1B", one combined F+B tick):

- F(i, m) at tick i + m — the GPipe forward flood, unchanged;
- B(i, m) at tick 2(P-1) - i + m — each cotangent drains back the moment
  it exists: the LAST stage runs B(m) in the same tick as its input
  arrives (head + loss fold into its vjp, loss cotangent = 1), stage i
  one tick after stage i+1;
- total ticks T = M + 2(P-1) vs GPipe's fwd+bwd 2(M+P-1); in-flight
  microbatches at stage i are bounded by 2(P-1-i)+1 <= 2P-1 = the stash.

SPMD form mirrors _pipeline_local: ONE jitted program, partial-manual
shard_map over {'pipe', 'data'} (tensor/seq axes stay GSPMD-automatic
inside the stage body, so TP/SP compose exactly as in GPipe), activations
and cotangents hop via paired forward/backward `lax.ppermute`s every
tick. Within a tick, work is masked, not branched: every device executes
the same compute and gates results by schedule validity (collectives
would deadlock under divergent control flow, so masking is the safe SPMD
idiom). ACROSS ticks, validity is static — so the schedule is three
scans, not one (round 4): fill (first P-1 ticks, F-only — no stage has
a valid backward yet), steady (M-1 ticks, F+B), drain (last P ticks,
B-only — all forwards are done). Bubble ticks no longer pay the other
sub-phase's compute: fill skips the vjp re-run + head entirely, drain
skips the forward and its hop. The remaining (inherent) masking cost is
per-STAGE idle work inside valid ticks. The price vs GPipe at equal M is
the longer combined schedule; the purchase is O(P) activation memory.
BENCHMARKS.md records both sides of that trade, measured.

Boundary values (hops, stash, psums) stay fp32 — same JAX 0.9
partial-manual sub-fp32 psum CHECK-failure workaround as pipeline.py;
stage compute still runs in the model's own (bf16) dtype inside the vjp.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ddp_practice_tpu.config import MeshConfig
from ddp_practice_tpu.parallel.ring import get_current_mesh
from ddp_practice_tpu.parallel.compat import shard_map


def _head_cond(head_loss_fn, head_params, y_b, tgt, wgt, aux_shape,
               is_head):
    """The last-stage head+loss vjp under lax.cond — ONE definition for
    both schedules (plain 1F1B and interleaved). `is_head` is uniform
    across a device's tensor/seq shards, so GSPMD collectives inside the
    taken branch stay lockstep. Returns (loss_sum, aux, dhp, dy)."""
    f32 = jnp.float32

    def do_head(operands):
        hp_, y_ = operands
        loss_sum, h_vjp, aux = jax.vjp(
            lambda h, yy: head_loss_fn(h, yy, tgt, wgt),
            hp_, y_, has_aux=True,
        )
        dhp, dy = h_vjp(jnp.ones((), loss_sum.dtype))
        return loss_sum, aux, dhp, dy.astype(f32)

    def skip_head(operands):
        hp_, y_ = operands
        return (
            jnp.zeros((), f32),
            jax.tree.map(lambda a: jnp.zeros((), f32), aux_shape),
            jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype), hp_),
            jnp.zeros_like(y_),
        )

    return lax.cond(is_head, do_head, skip_head, (head_params, y_b))


def _reduce_outputs(axis_name, dsp_acc, dhp_acc, loss_acc, aux_acc,
                    dxs_buf):
    """Final psums shared by both schedule kernels: grads/loss sum over
    'data'; last-stage-only values replicate over 'pipe' via the
    masked-psum idiom (accumulators are zero off their producing stage,
    so a plain psum IS the mask)."""
    data = MeshConfig.AXIS_DATA
    loss = lax.psum(loss_acc, (axis_name, data))
    aux = jax.tree.map(lambda a: lax.psum(a, (axis_name, data)), aux_acc)
    stage_grads = jax.tree.map(lambda g: lax.psum(g, data)[None], dsp_acc)
    head_grads = jax.tree.map(
        lambda g: lax.psum(g, (axis_name, data)), dhp_acc
    )
    dxs = lax.psum(dxs_buf, axis_name)
    return loss, aux, stage_grads, head_grads, dxs


def pipeline_1f1b_loss_and_grad(
    block_fn: Callable,
    head_loss_fn: Callable,
    stage_params,
    head_params,
    xs: jnp.ndarray,
    targets: jnp.ndarray,
    weights: jnp.ndarray,
    *,
    num_microbatches: int,
    compute_dtype=jnp.float32,
    axis_name: str = MeshConfig.AXIS_PIPE,
    mesh=None,
):
    """Run the 1F1B schedule; return loss/metric sums, grads and dx.

    block_fn(stage_params_local, x_mb) -> y_mb: one stage's blocks
    (leading leaf dim of `stage_params` = global stage count, as in
    pipeline_apply). head_loss_fn(head_params, y_mb, targets_mb,
    weights_mb) -> (loss_sum, aux) applies the head and a SUM-reduced
    loss for one microbatch; `aux` is a pytree of fp32 SCALARS (e.g.
    weight and correct-prediction counts) accumulated across microbatches
    and summed over every axis. Deliberately scalars only: full logits
    would put an (M, mb, s, V) buffer in the scan carry of EVERY stage
    and a V-wide psum at the end — at real vocab sizes that single
    metrics buffer dwarfs the O(P) activation stash this schedule exists
    to provide.

    xs: (M, mb, ...) fp32 embedded activations, microbatch dim first,
    per-microbatch batch sharded over 'data'. targets/weights: (M, mb, s).

    Returns (loss_sum, aux_sums, stage_grads, head_grads, dxs
    (M, mb, ...)): loss/aux/grads summed over 'data' (and replicated over
    'pipe'); dxs keeps the microbatch layout for the caller to un-permute
    into its embedding vjp. Grads are of the loss SUM — divide by the
    caller's token count for mean-loss gradients.
    """
    mesh = mesh or get_current_mesh()
    if mesh is None:
        raise ValueError(
            "pipeline_1f1b needs a mesh (set via parallel.ring.set_current_mesh)"
        )
    data = MeshConfig.AXIS_DATA
    mb_spec = P(None, data)  # microbatch dim replicated, batch over 'data'
    param_spec = jax.tree.map(lambda _: P(axis_name), stage_params)
    head_spec = jax.tree.map(lambda _: P(), head_params)
    fn = shard_map(
        functools.partial(
            _1f1b_local,
            block_fn=block_fn,
            head_loss_fn=head_loss_fn,
            num_mb=num_microbatches,
            axis_name=axis_name,
            compute_dtype=compute_dtype,
        ),
        mesh=mesh,
        in_specs=(param_spec, head_spec, mb_spec, mb_spec, mb_spec),
        out_specs=(P(), P(), param_spec, head_spec, mb_spec),
        axis_names=frozenset({axis_name, data}),
        check_vma=False,
    )
    return jax.jit(fn)(
        stage_params, head_params, xs.astype(jnp.float32), targets, weights
    )


def pipeline_interleaved_loss_and_grad(
    block_fn: Callable,
    head_loss_fn: Callable,
    stage_params,
    head_params,
    xs: jnp.ndarray,
    targets: jnp.ndarray,
    weights: jnp.ndarray,
    *,
    num_microbatches: int,
    num_virtual: int = 2,
    compute_dtype=jnp.float32,
    axis_name: str = MeshConfig.AXIS_PIPE,
    mesh=None,
):
    """Interleaved (virtual-stage) 1F1B — Megatron §2.2 on the masked-SPMD
    scan machinery.

    Same contract as pipeline_1f1b_loss_and_grad, except `stage_params`'
    leading leaf dim is S = num_virtual * P logical stages (stage
    s = v*P + i runs as chunk v on device i), and the schedule comes
    from constant tables (parallel/interleave.py: generated at trace
    time, dependency-validated by its own tests). Each device executes
    ONE chunk-op per tick (lax.cond picks the F or B body — `kind` is
    uniform across a device's tensor/seq shards, so collectives inside
    the branch stay lockstep); activations and cotangents ride the same
    single fwd/bwd ppermute pair per tick, with chunk-boundary hops
    (device P-1 -> 0 forward, 0 -> P-1 backward) carried by the ring
    wrap and re-keyed by the RECEIVER from the sender's table row. The
    purchase over plain 1F1B is the bubble: fill/drain ramps cost P
    ticks per chunk instead of P*V (measured table: P=4, M=8 idle
    fraction 0.273 -> 0.158 at V=2; BENCHMARKS.md schedule table)."""
    import numpy as np

    from ddp_practice_tpu.parallel.interleave import build_tables

    mesh = mesh or get_current_mesh()
    if mesh is None:
        raise ValueError(
            "pipeline_interleaved needs a mesh (set_current_mesh)"
        )
    P_ = mesh.shape[axis_name]
    V = num_virtual
    tables = build_tables(P_, V, num_microbatches)
    data = MeshConfig.AXIS_DATA
    mb_spec = P(None, data)
    # (S, ...) logical-stage params -> (P, V, ...): device i holds chunks
    # [i, P+i, ...] (stage s = v*P + i)
    def to_device_major(p):
        return jnp.swapaxes(
            p.reshape((V, P_) + p.shape[1:]), 0, 1
        )

    dev_params = jax.tree.map(to_device_major, stage_params)
    param_spec = jax.tree.map(lambda _: P(axis_name), dev_params)
    head_spec = jax.tree.map(lambda _: P(), head_params)
    fn = shard_map(
        functools.partial(
            _interleaved_local,
            block_fn=block_fn,
            head_loss_fn=head_loss_fn,
            num_mb=num_microbatches,
            num_virtual=V,
            axis_name=axis_name,
            compute_dtype=compute_dtype,
            kind_tab=tables.kind, chunk_tab=tables.chunk,
            mb_tab=tables.mb,
        ),
        mesh=mesh,
        in_specs=(param_spec, head_spec, mb_spec, mb_spec, mb_spec),
        out_specs=(P(), P(), param_spec, head_spec, mb_spec),
        axis_names=frozenset({axis_name, data}),
        check_vma=False,
    )
    loss, aux, dev_grads, head_grads, dxs = jax.jit(fn)(
        dev_params, head_params, xs.astype(jnp.float32), targets, weights
    )
    # back to (S, ...) logical-stage layout
    def to_stage_major(g):
        return jnp.swapaxes(g, 0, 1).reshape(
            (V * P_,) + g.shape[2:]
        )

    return loss, aux, jax.tree.map(to_stage_major, dev_grads), head_grads, dxs


def _interleaved_local(dev_params, head_params, xs, targets, weights, *,
                       block_fn, head_loss_fn, num_mb, num_virtual,
                       axis_name, compute_dtype, kind_tab, chunk_tab,
                       mb_tab):
    sp = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), dev_params)  # (V,...)
    n_stages = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    M, V = num_mb, num_virtual
    mb_shape = xs.shape[1:]
    T = kind_tab.shape[0]
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    f32 = jnp.float32
    kind_c = jnp.asarray(kind_tab)    # (T, P) int32 constants
    chunk_c = jnp.asarray(chunk_tab)
    mb_c = jnp.asarray(mb_tab)

    def fwd_chunk(sp_, v, x_):
        """One chunk's blocks, chunk picked by traced v via lax.switch
        (vjp flows only through the taken branch — off-chunk param
        grads come out zero, which is exactly the masked accumulate)."""
        return lax.switch(
            v,
            [
                (lambda xx, vv=vv: block_fn(
                    jax.tree.map(lambda p: p[vv], sp_),
                    xx.astype(compute_dtype),
                ).astype(f32))
                for vv in range(V)
            ],
            x_,
        )

    aux_shape = jax.eval_shape(
        lambda hp, y, t, w: head_loss_fn(hp, y, t, w)[1],
        head_params, jnp.zeros(mb_shape, f32), targets[0], weights[0],
    )

    def tick(carry, t):
        (act_buf, dy_buf, stash, dsp_acc, dhp_acc, loss_acc, aux_acc,
         dxs_buf) = carry
        krow = lax.dynamic_index_in_dim(kind_c, t, 0, False)   # (P,)
        crow = lax.dynamic_index_in_dim(chunk_c, t, 0, False)
        mrow = lax.dynamic_index_in_dim(mb_c, t, 0, False)
        my_k, my_v, my_m = krow[idx], crow[idx], mrow[idx]
        # buffers key on the raw microbatch index: interleaved in-flight
        # counts per (device, chunk) reach M (chunk 0's backwards all run
        # last), so the plain-1F1B 2P-1 ring would collide — O(M*V)
        # activation state is the documented Megatron trade for the
        # V-fold smaller bubble
        slot = jnp.clip(my_m, 0, M - 1)

        # ---- forward body (kind == 1) ----
        def do_f(ops):
            act_buf, stash, *_rest = ops
            x_in = jnp.where(
                (my_v == 0) & (idx == 0),
                lax.dynamic_index_in_dim(
                    xs, jnp.clip(my_m, 0, M - 1), 0, False
                ),
                act_buf[my_v, slot],
            )
            y = fwd_chunk(sp, my_v, x_in)
            stash = stash.at[my_v, slot].set(x_in)
            return y, stash

        def skip_f(ops):
            return jnp.zeros(mb_shape, f32), ops[1]

        y_f, stash = lax.cond(my_k == 1, do_f, skip_f, (act_buf, stash))
        y_hop = lax.ppermute(y_f, axis_name, fwd_perm)
        # receiver files the arrival under the SENDER's table row
        prev = (idx - 1) % n_stages
        sv = crow[prev]
        recv_v = jnp.where(idx == 0, sv + 1, sv)
        recv_ok = (krow[prev] == 1) & (recv_v < V)
        act_buf = jnp.where(
            recv_ok,
            act_buf.at[jnp.clip(recv_v, 0, V - 1),
                       jnp.clip(mrow[prev], 0, M - 1)].set(y_hop),
            act_buf,
        )

        # ---- backward body (kind == 2) ----
        def do_b(ops):
            dy_buf_, stash_ = ops
            x_b = stash_[my_v, slot]
            y_b, blocks_vjp = jax.vjp(
                lambda p_, x_: fwd_chunk(p_, my_v, x_), sp, x_b
            )
            tgt = lax.dynamic_index_in_dim(
                targets, jnp.clip(my_m, 0, M - 1), 0, False
            )
            wgt = lax.dynamic_index_in_dim(
                weights, jnp.clip(my_m, 0, M - 1), 0, False
            )
            is_head = (idx == n_stages - 1) & (my_v == V - 1)
            loss_m, aux_m, dhp_m, dy_head = _head_cond(
                head_loss_fn, head_params, y_b, tgt, wgt, aux_shape,
                is_head,
            )
            dy_ct = jnp.where(is_head, dy_head, dy_buf_[my_v, slot])
            dsp_m, dx_m = blocks_vjp(dy_ct)
            # f32 so both cond branches agree regardless of param dtype
            dsp_m = jax.tree.map(lambda g: g.astype(f32), dsp_m)
            return loss_m, aux_m, dhp_m, dsp_m, dx_m.astype(f32), is_head

        def skip_b(ops):
            return (
                jnp.zeros((), f32),
                jax.tree.map(lambda a: jnp.zeros((), f32), aux_shape),
                jax.tree.map(
                    lambda p: jnp.zeros(p.shape, p.dtype), head_params
                ),
                jax.tree.map(lambda p: jnp.zeros(p.shape, f32), sp),
                jnp.zeros(mb_shape, f32),
                jnp.asarray(False),
            )

        b_on = my_k == 2
        loss_m, aux_m, dhp_m, dsp_m, dx_m, is_head = lax.cond(
            b_on, do_b, skip_b, (dy_buf, stash)
        )
        bmask = b_on.astype(f32)
        dsp_acc = jax.tree.map(
            lambda a, gr: a + gr.astype(f32) * bmask, dsp_acc, dsp_m
        )
        dhp_acc = jax.tree.map(
            lambda a, gr: a + gr.astype(f32) * bmask, dhp_acc, dhp_m
        )
        emit = b_on & is_head
        loss_acc = loss_acc + jnp.where(emit, loss_m, 0.0)
        aux_acc = jax.tree.map(
            lambda a, v_: a + jnp.where(emit, v_.astype(f32), 0.0),
            aux_acc, aux_m,
        )
        dxs_buf = jnp.where(
            b_on & (idx == 0) & (my_v == 0),
            lax.dynamic_update_index_in_dim(
                dxs_buf, dx_m.astype(f32), jnp.clip(my_m, 0, M - 1), 0
            ),
            dxs_buf,
        )
        dx_hop = lax.ppermute(dx_m, axis_name, bwd_perm)
        nxt = (idx + 1) % n_stages
        rv = crow[nxt]
        recv_bv = jnp.where(idx == n_stages - 1, rv - 1, rv)
        recv_ok_b = (krow[nxt] == 2) & (recv_bv >= 0)
        dy_buf = jnp.where(
            recv_ok_b,
            dy_buf.at[jnp.clip(recv_bv, 0, V - 1),
                      jnp.clip(mrow[nxt], 0, M - 1)].set(dx_hop),
            dy_buf,
        )
        return (act_buf, dy_buf, stash, dsp_acc, dhp_acc, loss_acc,
                aux_acc, dxs_buf), None

    carry = (
        jnp.zeros((V, M) + mb_shape, f32),            # act inbox
        jnp.zeros((V, M) + mb_shape, f32),            # dy inbox
        jnp.zeros((V, M) + mb_shape, f32),            # stash
        jax.tree.map(lambda p: jnp.zeros(p.shape, f32), sp),
        jax.tree.map(lambda p: jnp.zeros(p.shape, f32), head_params),
        jnp.zeros((), f32),
        jax.tree.map(lambda a: jnp.zeros((), f32), aux_shape),
        jnp.zeros((M,) + mb_shape, f32),
    )
    carry, _ = lax.scan(tick, carry, jnp.arange(T))
    (_, _, _, dsp_acc, dhp_acc, loss_acc, aux_acc, dxs_buf) = carry
    return _reduce_outputs(
        axis_name, dsp_acc, dhp_acc, loss_acc, aux_acc, dxs_buf
    )


def _1f1b_local(stage_params, head_params, xs, targets, weights, *,
                block_fn, head_loss_fn, num_mb, axis_name, compute_dtype):
    sp = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), stage_params)
    n_stages = lax.psum(1, axis_name)  # trace-time constant
    idx = lax.axis_index(axis_name)
    M = xs.shape[0]
    assert M == num_mb, (M, num_mb)
    mb_shape = xs.shape[1:]
    W = 2 * n_stages - 1               # stash ring: max in-flight per stage
    T = M + 2 * (n_stages - 1)
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    f32 = jnp.float32

    def fwd(sp_, x_):
        return block_fn(sp_, x_.astype(compute_dtype)).astype(f32)

    def make_tick(do_f: bool, do_b: bool):
        """One schedule tick, specialized to its phase. Tick validity is
        STATIC per phase (round 4 fill/steady/drain split): fill ticks
        carry no valid B anywhere, drain ticks no valid F — so the
        specialized bodies simply omit that sub-phase's compute and hop
        instead of running it masked. Within a phase every device still
        executes the same program (collectives stay lockstep)."""

        def tick(carry, t):
            (stash, y_in, dy_in, dsp_acc, dhp_acc, loss_acc, aux_acc,
             dxs_buf) = carry

            if do_f:
                # ---- F sub-phase: stage i forwards microbatch t - i
                fm = t - idx
                f_valid = (fm >= 0) & (fm < M) & (idx < n_stages - 1)
                fm_c = jnp.clip(fm, 0, M - 1)
                x_f = jnp.where(
                    idx == 0,
                    lax.dynamic_index_in_dim(xs, fm_c, 0, False), y_in,
                )
                y_f = fwd(sp, x_f)
                stash = jnp.where(
                    f_valid,
                    lax.dynamic_update_index_in_dim(stash, x_f, fm_c % W, 0),
                    stash,
                )
                # activations hop forward; invalid slots carry garbage —
                # every consumer gates by its own schedule
                y_next = lax.ppermute(y_f, axis_name, fwd_perm)
            else:
                # drain: all forwards are done; the inbox must PERSIST —
                # the last stage consumes its final activation on the
                # first drain tick
                y_next = y_in

            if not do_b:
                # fill: no stage has a valid backward yet
                return (stash, y_next, dy_in, dsp_acc, dhp_acc, loss_acc,
                        aux_acc, dxs_buf), None

            # ---- B sub-phase: stage i backwards microbatch
            # t - (2(P-1) - i). Blocks re-run under jax.vjp on every
            # stage (that is the work); the vocab-wide head + loss runs
            # under lax.cond on the LAST stage only — `is_last` is
            # uniform across the 'tensor'/'seq' shards of a stage, so
            # GSPMD collectives inside the branch are taken (or skipped)
            # by every member of their group together. Elsewhere the
            # cotangent flows in from the next stage's B of the previous
            # tick.
            bm = t - (2 * (n_stages - 1) - idx)
            b_valid = (bm >= 0) & (bm < M)
            bm_c = jnp.clip(bm, 0, M - 1)
            is_last = idx == n_stages - 1
            # last stage consumes straight from its inbox (it never
            # forwards); a single-stage pipeline (last AND first) reads
            # the source batch
            x_b = jnp.where(
                is_last,
                jnp.where(
                    idx == 0,
                    lax.dynamic_index_in_dim(xs, bm_c, 0, False), y_in,
                ),
                lax.dynamic_index_in_dim(stash, bm_c % W, 0, False),
            )
            tgt = lax.dynamic_index_in_dim(targets, bm_c, 0, False)
            wgt = lax.dynamic_index_in_dim(weights, bm_c, 0, False)

            y_b, blocks_vjp = jax.vjp(fwd, sp, x_b)
            loss_m, aux_m, dhp_m, dy_head = _head_cond(
                head_loss_fn, head_params, y_b, tgt, wgt, aux_shape,
                is_last,
            )
            zero_f = jnp.asarray(0.0, f32)
            dy_ct = jnp.where(is_last, dy_head, dy_in)
            dsp_m, dx_m = blocks_vjp(dy_ct)

            bmask = b_valid.astype(f32)
            dsp_acc = jax.tree.map(
                lambda a, gr: a + gr.astype(f32) * bmask, dsp_acc, dsp_m
            )
            dhp_acc = jax.tree.map(
                lambda a, gr: a + gr.astype(f32) * bmask, dhp_acc, dhp_m
            )
            emit = b_valid & is_last
            loss_acc = loss_acc + jnp.where(emit, loss_m, zero_f)
            aux_acc = jax.tree.map(
                lambda a, v: a + jnp.where(emit, v.astype(f32), zero_f),
                aux_acc, aux_m,
            )
            dxs_buf = jnp.where(
                b_valid & (idx == 0),
                lax.dynamic_update_index_in_dim(
                    dxs_buf, dx_m.astype(f32), bm_c, 0
                ),
                dxs_buf,
            )

            # cotangents hop backward
            dy_next = lax.ppermute(dx_m.astype(f32), axis_name, bwd_perm)
            return (stash, y_next, dy_next, dsp_acc, dhp_acc, loss_acc,
                    aux_acc, dxs_buf), None

        return tick

    aux_shape = jax.eval_shape(
        lambda hp, y, t, w: head_loss_fn(hp, y, t, w)[1],
        head_params, jnp.zeros(mb_shape, f32), targets[0], weights[0],
    )
    carry = (
        jnp.zeros((W,) + mb_shape, f32),            # stash
        jnp.zeros(mb_shape, f32),                   # y inbox
        jnp.zeros(mb_shape, f32),                   # dy inbox
        jax.tree.map(lambda p: jnp.zeros(p.shape, f32), sp),
        jax.tree.map(lambda p: jnp.zeros(p.shape, f32), head_params),
        jnp.zeros((), f32),                         # loss sum
        jax.tree.map(lambda a: jnp.zeros((), f32), aux_shape),
        jnp.zeros((M,) + mb_shape, f32),            # dxs
    )
    # phase boundaries (static): the last valid F anywhere is stage P-2's
    # microbatch M-1 at tick M+P-3; the first valid B anywhere is the
    # last stage's microbatch 0 at tick P-1. fill = [0, P-2] F-only,
    # steady = [P-1, M+P-3] F+B, drain = [M+P-2, T-1] B-only. Lengths
    # (P-1) + (M-1) + P = T. Empty phases (P=1, M=1) scan zero ticks.
    P_ = n_stages
    fill_end = P_ - 1
    steady_end = M + P_ - 2
    carry, _ = lax.scan(make_tick(True, False), carry,
                        jnp.arange(0, fill_end))
    carry, _ = lax.scan(make_tick(True, True), carry,
                        jnp.arange(fill_end, steady_end))
    carry, _ = lax.scan(make_tick(False, True), carry,
                        jnp.arange(steady_end, T))
    (_, _, _, dsp_acc, dhp_acc, loss_acc, aux_acc, dxs_buf) = carry
    return _reduce_outputs(
        axis_name, dsp_acc, dhp_acc, loss_acc, aux_acc, dxs_buf
    )
