"""Ring attention: sequence/context parallelism over the 'seq' mesh axis.

Absent from the reference (no attention, no sequence axis — SURVEY §5.7) but
first-class here: the sequence dimension is sharded across devices; each
device computes blockwise attention for its local queries while K/V blocks
rotate around the ring via `lax.ppermute` (ICI neighbor exchange), with an
online-softmax accumulator so the result is exact — the Ring Attention
construction (Liu et al.) on top of XLA collectives.

Works in two modes:
- already inside a `shard_map`/pmap where `axis_name` is bound: computes
  directly on the local blocks.
- under GSPMD `jit`: wraps itself in a `shard_map` island over the current
  mesh (batch dim over 'data', sequence dim over `axis_name`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ddp_practice_tpu.config import MeshConfig
from ddp_practice_tpu.parallel.compat import shard_map

_NEG_INF = -1e30

# Mesh registry so model code deep inside a jitted function can open a
# shard_map island without threading the Mesh object through every module.
_CURRENT_MESH = None


def set_current_mesh(mesh) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def get_current_mesh():
    return _CURRENT_MESH


def single_chip_tpu() -> bool:
    """True when the program executes compiled on ONE TPU chip.

    The auto-selection gate for kernel-by-default paths (currently
    models/vit.py EncoderBlock._auto_fuse; MoE's "auto" resolved to the
    einsum path everywhere once the gather/sorted shootout measured it
    fastest, so MoEMlp no longer consults this): Pallas kernels run
    interpret-mode on CPU (never a win) and are not
    validated under multi-chip GSPMD partitioning here, so implicit
    selection stays out of both regimes. "One chip" means the devices
    this program runs on — the framework's current mesh when set
    (a --devices 1 run on a multi-chip host qualifies), the host
    inventory otherwise."""
    import jax

    if jax.default_backend() != "tpu":
        return False
    mesh = get_current_mesh()
    n_dev = mesh.devices.size if mesh is not None else jax.device_count()
    return n_dev == 1


def _axis_bound(axis_name: str) -> bool:
    try:
        lax.axis_index(axis_name)
        return True
    except (NameError, KeyError, ValueError):
        return False


def ring_attention(
    q: jnp.ndarray,  # (batch, seq_local_or_global, heads, head_dim)
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = False,
    mesh=None,
    impl: str = "xla",
) -> jnp.ndarray:
    """impl='xla': inline blockwise einsums (online softmax). impl='flash':
    the Pallas kernel (ops.flash_attention) runs each local q x k-block
    attention, returning (out, lse); partials merge across ring steps in
    logsumexp space — O(local seq) memory with the fused kernel's HBM
    profile, composing the two long-context features."""
    if _axis_bound(axis_name):
        return _ring_attention_local(
            q, k, v, axis_name=axis_name, causal=causal, impl=impl
        )
    mesh = mesh or get_current_mesh()
    if mesh is None:
        raise ValueError(
            "ring_attention outside shard_map needs a mesh "
            "(set via parallel.ring.set_current_mesh)"
        )
    # batch over data, sequence over the ring axis, heads stay sharded over
    # tensor (heads are independent in attention, so TP composes with SP)
    mesh, spec = _island_mesh_and_spec(mesh, axis_name)
    fn = shard_map(
        functools.partial(
            _ring_attention_local, axis_name=axis_name, causal=causal,
            impl=impl,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def _island_mesh_and_spec(mesh, axis_name: str):
    """Mesh + (batch, seq, heads, None) spec for an SP shard_map island.

    Under an OUTER partial-manual shard_map (the GPipe pipeline is manual
    over 'pipe'/'data'), a nested island must (a) pass the context
    AbstractMesh, whose axis_types record which axes are already Manual,
    and (b) name only still-automatic axes in its specs — the manual ones
    are already local dims here. That is what lets sequence parallelism
    run INSIDE a pipeline stage (sp x pp)."""
    try:
        from jax.sharding import AxisType

        ctx = jax.sharding.get_abstract_mesh()
        manual = {
            n for n, t in zip(ctx.axis_names, ctx.axis_types)
            if t == AxisType.Manual
        }
    except Exception:
        ctx, manual = None, set()
    if manual:
        if axis_name in manual:
            raise ValueError(
                f"sequence axis {axis_name!r} is already manual in the "
                "enclosing shard_map — call the local ring directly"
            )
        mesh = ctx
    spec = P(
        None if MeshConfig.AXIS_DATA in manual else MeshConfig.AXIS_DATA,
        axis_name,
        None if MeshConfig.AXIS_TENSOR in manual else MeshConfig.AXIS_TENSOR,
        None,
    )
    return mesh, spec


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          impl: str = "xla"):
    if impl == "flash":
        return _ring_flash_local(q, k, v, axis_name=axis_name, causal=causal)
    if impl != "xla":
        raise ValueError(f"unknown attention impl {impl!r} (want 'xla'|'flash')")
    return _ring_xla_local(q, k, v, axis_name=axis_name, causal=causal)


def _ring_flash_local(q, k, v, *, axis_name: str, causal: bool):
    """Ring attention with the Pallas flash kernel as the local attention.

    Each ring step computes flash attention of the (resident) local queries
    against the currently-held K/V block, yielding normalized (o_i, lse_i);
    partials merge exactly:

        m = max(lse, lse_i); w = exp(lse - m); w_i = exp(lse_i - m)
        o <- (w*o + w_i*o_i) / (w + w_i);  lse <- m + log(w + w_i)

    Causality across blocks resolves by block index (this device holds
    global q positions [my_idx*sq, ...)): earlier blocks attend fully,
    the diagonal block runs the kernel's causal mask, later blocks are
    skipped (lse = -inf) — gradients flow through the kernel's tiled
    backward plus the (differentiable) merge."""
    from ddp_practice_tpu.ops.flash_attention import flash_attention_with_lse

    in_dtype = q.dtype
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, dh = q.shape

    def fold(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, x.shape[1], dh)

    qf, kf, vf = fold(q), fold(k), fold(v)
    o0 = jnp.zeros((b * h, sq, dh), jnp.float32)
    lse0 = jnp.full((b * h, sq), _NEG_INF, jnp.float32)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def attend(kb, vb, kblock):
        def run(causal_flag):
            def f(args):
                o, lse = flash_attention_with_lse(*args, causal=causal_flag)
                return o.astype(jnp.float32), lse
            return f

        if not causal:
            return run(False)((qf, kb, vb))

        def masked(args):
            return (jnp.zeros((b * h, sq, dh), jnp.float32),
                    jnp.full((b * h, sq), _NEG_INF, jnp.float32))

        idx = jnp.where(kblock == my_idx, 1, jnp.where(kblock < my_idx, 2, 0))
        return lax.switch(idx, [masked, run(True), run(False)], (qf, kb, vb))

    def body(carry, step):
        o, lse, kb, vb = carry
        kblock = (my_idx - step) % axis_size
        oi, lsei = attend(kb, vb, kblock)
        m = jnp.maximum(lse, lsei)
        w1 = jnp.exp(lse - m)
        w2 = jnp.exp(lsei - m)
        denom = w1 + w2
        o = (o * w1[..., None] + oi * w2[..., None]) / denom[..., None]
        lse = m + jnp.log(denom)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (o, lse, kb, vb), None

    (o, _, _, _), _ = lax.scan(
        body, (o0, lse0, kf, vf), jnp.arange(axis_size)
    )
    o = jnp.transpose(o.reshape(b, h, sq, dh), (0, 2, 1, 3))
    return o.astype(in_dtype)


def _ring_xla_local(q, k, v, *, axis_name: str, causal: bool):
    """Blockwise attention on local shards; K/V ring-rotated each step."""
    in_dtype = q.dtype
    axis_size = lax.psum(1, axis_name)  # trace-time constant under shard_map
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    qf = jnp.transpose(q, (0, 2, 1, 3)).astype(jnp.float32)  # (b,h,sq,d)
    kf = jnp.transpose(k, (0, 2, 1, 3)).astype(jnp.float32)
    vf = jnp.transpose(v, (0, 2, 1, 3)).astype(jnp.float32)

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)

    q_pos = my_idx * sq + jnp.arange(sq)  # global query positions

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(carry, step):
        o, m, l, kb, vb = carry
        kblock = (my_idx - step) % axis_size
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb) * scale
        if causal:
            k_pos = kblock * sk + jnp.arange(sk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard exp(-inf - -inf): rows still fully masked keep m at _NEG_INF
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (o_new, m_new, l_new, kb, vb), None

    (o, m, l, _, _), _ = lax.scan(
        body, (o0, m0, l0, kf, vf), jnp.arange(axis_size)
    )
    o = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(o, (0, 2, 1, 3)).astype(in_dtype)
