"""Ring attention: sequence/context parallelism over the 'seq' mesh axis.

Absent from the reference (no attention, no sequence axis — SURVEY §5.7) but
first-class here: the sequence dimension is sharded across devices; each
device computes blockwise attention for its local queries while K/V blocks
rotate around the ring via `lax.ppermute` (ICI neighbor exchange), with an
online-softmax accumulator so the result is exact — the Ring Attention
construction (Liu et al.) on top of XLA collectives.

Works in two modes:
- already inside a `shard_map`/pmap where `axis_name` is bound: computes
  directly on the local blocks.
- under GSPMD `jit`: wraps itself in a `shard_map` island over the current
  mesh (batch dim over 'data', sequence dim over `axis_name`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ddp_practice_tpu.config import MeshConfig

_NEG_INF = -1e30

# Mesh registry so model code deep inside a jitted function can open a
# shard_map island without threading the Mesh object through every module.
_CURRENT_MESH = None


def set_current_mesh(mesh) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def get_current_mesh():
    return _CURRENT_MESH


def _axis_bound(axis_name: str) -> bool:
    try:
        lax.axis_index(axis_name)
        return True
    except (NameError, KeyError, ValueError):
        return False


def ring_attention(
    q: jnp.ndarray,  # (batch, seq_local_or_global, heads, head_dim)
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = False,
    mesh=None,
) -> jnp.ndarray:
    if _axis_bound(axis_name):
        return _ring_attention_local(q, k, v, axis_name=axis_name, causal=causal)
    mesh = mesh or get_current_mesh()
    if mesh is None:
        raise ValueError(
            "ring_attention outside shard_map needs a mesh "
            "(set via parallel.ring.set_current_mesh)"
        )
    # batch over data, sequence over the ring axis, heads stay sharded over
    # tensor (heads are independent in attention, so TP composes with SP)
    spec = P(MeshConfig.AXIS_DATA, axis_name, MeshConfig.AXIS_TENSOR, None)
    fn = jax.shard_map(
        functools.partial(
            _ring_attention_local, axis_name=axis_name, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool):
    """Blockwise attention on local shards; K/V ring-rotated each step."""
    in_dtype = q.dtype
    axis_size = lax.psum(1, axis_name)  # trace-time constant under shard_map
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    qf = jnp.transpose(q, (0, 2, 1, 3)).astype(jnp.float32)  # (b,h,sq,d)
    kf = jnp.transpose(k, (0, 2, 1, 3)).astype(jnp.float32)
    vf = jnp.transpose(v, (0, 2, 1, 3)).astype(jnp.float32)

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)

    q_pos = my_idx * sq + jnp.arange(sq)  # global query positions

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(carry, step):
        o, m, l, kb, vb = carry
        kblock = (my_idx - step) % axis_size
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb) * scale
        if causal:
            k_pos = kblock * sk + jnp.arange(sk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard exp(-inf - -inf): rows still fully masked keep m at _NEG_INF
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (o_new, m_new, l_new, kb, vb), None

    (o, m, l, _, _), _ = lax.scan(
        body, (o0, m0, l0, kf, vf), jnp.arange(axis_size)
    )
    o = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(o, (0, 2, 1, 3)).astype(in_dtype)
