"""FSDP / ZeRO-3: parameter + optimizer-state sharding over the 'data' axis.

The reference replicates parameters and optimizer state on every process
(SURVEY §2.3: "FSDP/ZeRO — No; full replication", ddp_main.py:117-125).
Here sharded training is a *layout choice*, not a wrapper: each parameter
leaf (and therefore its optimizer-state mirrors, which share shapes) is
given a PartitionSpec that shards its largest free dimension across the
'data' mesh axis. Under GSPMD `jit`, XLA then:

- all-gathers each parameter just before use in the forward/backward
  (ZeRO-3 semantics), scheduled/overlapped by the latency-hiding scheduler;
- reduce-scatters gradients so each device updates only its own shard
  (the ZeRO optimizer-state partitioning), instead of the DDP-style
  all-reduce + replicated update.

No hand-written collectives: the spec IS the strategy. Composes with
tensor-parallel rules — TP claims its axis first, FSDP shards a remaining
free dimension over 'data'.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

from jax.sharding import PartitionSpec as P

from ddp_practice_tpu.config import MeshConfig


def fsdp_rules(
    data_axis_size: int,
    base_rules: Optional[Callable] = None,
    *,
    min_leaf_size: int = 1024,
) -> Callable:
    """Return rules(path, leaf) -> PartitionSpec adding 'data'-axis sharding.

    - Applies `base_rules` (e.g. tensor-parallel specs) first; FSDP only
      claims a dimension the base rules left unsharded.
    - Picks the largest dimension divisible by `data_axis_size` (weights are
      gathered whole anyway; the largest dim minimizes padding risk and
      balances shard bytes).
    - Leaves smaller than `min_leaf_size` elements stay as the base rules
      put them (tiny biases/scales aren't worth an all-gather).
    """

    def rules(path, leaf):
        base = base_rules(path, leaf) if base_rules is not None else None
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if data_axis_size <= 1 or not shape:
            return base
        if math.prod(shape) < min_leaf_size:
            return base
        spec = list(base) if base is not None else []
        spec += [None] * (len(shape) - len(spec))
        best_dim, best_size = None, 0
        for d, (size, taken) in enumerate(zip(shape, spec)):
            if taken is None and size % data_axis_size == 0 and size > best_size:
                best_dim, best_size = d, size
        if best_dim is None:
            return base
        spec[best_dim] = MeshConfig.AXIS_DATA
        return P(*spec)

    return rules
