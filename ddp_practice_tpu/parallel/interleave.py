"""Interleaved-1F1B schedule tables (virtual pipeline stages).

Megatron-LM's interleaved schedule (Narayanan et al. 2021, §2.2) cuts
the pipeline bubble by a factor of V: each device holds V CHUNKS of
layers (logical stage s = v*P + i on device i), so the fill/drain ramp
costs P ticks per chunk instead of P*V ticks for the whole depth —
bubble fraction 2(P-1)/(2(P-1) + M*V) per chunk group vs
2(P-1)V/(2(P-1)V + M*V) flat.

Rather than baking Megatron's per-device op-order formulas into masked
arithmetic (the round-4 1F1B style), this module GENERATES the schedule
in Python at trace time and hands the kernel constant (T, P) int32
tables — op kind/chunk/microbatch per device per tick. A greedy
dependency-respecting list scheduler over Megatron's op ORDER produces
the tables; `simulate()` replays them against the data dependencies and
is what the unit tests assert on (every F/B exactly once, every input
produced >= 1 tick before use, bubble below plain 1F1B's). The SPMD
kernel (pipeline_1f1b.py) then just indexes the tables — schedule
correctness and kernel correctness are tested separately.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Tables:
    """Constant schedule tables, all (T, P) int32. kind: 0 = idle,
    1 = forward, 2 = backward; chunk/mb meaningful where kind != 0."""

    kind: np.ndarray
    chunk: np.ndarray
    mb: np.ndarray

    @property
    def ticks(self) -> int:
        return self.kind.shape[0]

    def bubble_fraction(self) -> float:
        """Idle device-ticks over total device-ticks."""
        return float((self.kind == 0).mean())


def _megatron_op_order(P: int, V: int, M: int, i: int) -> List[Tuple]:
    """Device i's op sequence: ('F'|'B', chunk v, microbatch m), in
    Megatron's interleaved order — microbatches in groups of P,
    chunk-major within a group for forwards; warmup of
    2*(P-1-i) + (V-1)*P forwards, then 1F1B, then backward cooldown."""
    n_ops = M * V

    def f_id(k):  # k-th forward: group-of-P, chunk-major
        g, r = divmod(k, P * V)
        v, p = divmod(r, P)
        return ("F", v, g * P + p)

    def b_id(k):  # k-th backward: same order, chunks reversed
        g, r = divmod(k, P * V)
        v, p = divmod(r, P)
        return ("B", V - 1 - v, g * P + p)

    warmup = min((P - 1 - i) * 2 + (V - 1) * P, n_ops)
    ops: List[Tuple] = [f_id(k) for k in range(warmup)]
    nf, nb = warmup, 0
    # steady state: one F then one B per iteration (Megatron's
    # forward_step-then-backward_step loop); cooldown drains the Bs
    while nb < n_ops:
        if nf < n_ops:
            ops.append(f_id(nf))
            nf += 1
        ops.append(b_id(nb))
        nb += 1
    return ops


def build_tables(P: int, V: int, M: int) -> Tables:
    """Greedy list-schedule of the Megatron op order into global ticks.

    An op executes at tick t when its data dependency was PRODUCED at a
    tick < t (activations/cotangents hop between devices at tick
    boundaries via ppermute): F(v, m) on device i needs F(v, m) on
    i-1 (same chunk), or F(v-1, m) on device P-1 when i == 0 (chunk
    boundary — the ring wrap carries it); chunk 0 on device 0 reads the
    host input, always ready. B(v, m) on i needs B(v, m) on i+1, or
    B(v+1, m) on device 0 when i == P-1; the last stage's head
    additionally needs its own F(V-1, m) done (the stash holds x).
    Every device also needs its own F(v, m) before B(v, m)."""
    if M % P:
        raise ValueError(
            f"interleaved schedule needs microbatches ({M}) divisible by "
            f"pipe ({P}) — Megatron's group-of-P round robin"
        )
    orders = [_megatron_op_order(P, V, M, i) for i in range(P)]
    pos = [0] * P
    done: dict = {}
    kind_rows, chunk_rows, mb_rows = [], [], []
    t = 0
    guard = 10 * (M * V * 2 + 4 * P * V)
    while any(pos[i] < len(orders[i]) for i in range(P)):
        krow, crow, mrow = [0] * P, [0] * P, [0] * P
        fired = []
        for i in range(P):
            if pos[i] >= len(orders[i]):
                continue
            op, v, m = orders[i][pos[i]]
            if op == "F":
                if v == 0 and i == 0:
                    ready = True
                elif i > 0:
                    ready = done.get(("F", v, m, i - 1), t) < t
                else:
                    ready = done.get(("F", v - 1, m, P - 1), t) < t
            else:
                own_f = done.get(("F", v, m, i), t) < t
                if i == P - 1 and v == V - 1:
                    ready = own_f
                elif i < P - 1:
                    ready = own_f and done.get(("B", v, m, i + 1), t) < t
                else:
                    ready = own_f and done.get(("B", v + 1, m, 0), t) < t
            if ready:
                krow[i] = 1 if op == "F" else 2
                crow[i], mrow[i] = v, m
                fired.append((op, v, m, i))
        for key in fired:
            done[key] = t
            i = key[3]
            pos[i] += 1
        kind_rows.append(krow)
        chunk_rows.append(crow)
        mb_rows.append(mrow)
        t += 1
        if t > guard:
            raise RuntimeError(
                f"interleaved schedule did not converge (P={P}, V={V}, "
                f"M={M}) — dependency deadlock in the op order"
            )
    return Tables(
        kind=np.asarray(kind_rows, np.int32),
        chunk=np.asarray(chunk_rows, np.int32),
        mb=np.asarray(mb_rows, np.int32),
    )


def simulate(tables: Tables, P: int, V: int, M: int) -> None:
    """Replay the tables against the data dependencies; raise on any
    violation. The unit tests run this over a (P, V, M) grid."""
    done = {}
    for t in range(tables.ticks):
        fired = []
        for i in range(P):
            k = int(tables.kind[t, i])
            if k == 0:
                continue
            v, m = int(tables.chunk[t, i]), int(tables.mb[t, i])
            if k == 1:
                if not (v == 0 and i == 0):
                    src = (
                        ("F", v, m, i - 1) if i > 0
                        else ("F", v - 1, m, P - 1)
                    )
                    assert done.get(src, t) < t, (t, i, "F", v, m, src)
                fired.append(("F", v, m, i))
            else:
                assert done.get(("F", v, m, i), t) < t, (t, i, "B-own", v, m)
                if not (i == P - 1 and v == V - 1):
                    src = (
                        ("B", v, m, i + 1) if i < P - 1
                        else ("B", v + 1, m, 0)
                    )
                    assert done.get(src, t) < t, (t, i, "B", v, m, src)
                fired.append(("B", v, m, i))
        for key in fired:
            assert key not in done, ("duplicate", key)
            done[key] = t
    want = 2 * P * V * M
    assert len(done) == want, (len(done), want)
