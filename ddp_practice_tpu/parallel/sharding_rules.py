"""Tensor-parallel parameter sharding rules.

Megatron-style TP for the transformer family, expressed the TPU way: not
manual collectives but `PartitionSpec`s on parameter leaves — XLA/GSPMD
inserts the all-reduces (over ICI) at the row-parallel boundaries. Rules key
on parameter *path names*, so they apply equally to the optimizer-state
mirrors of each parameter (optax momentum/adam trees repeat the names).

Column-parallel (shard output features over 'tensor'): attention QKV,
MLP fc_in. Row-parallel (shard input features): attention out, MLP fc_out.
The reference has no model parallelism at all (SURVEY §2.3) — this is new
capability the mesh design carries from day one.
"""

from __future__ import annotations

from typing import Callable, Optional

from jax.sharding import PartitionSpec as P
from jax.tree_util import keystr

from ddp_practice_tpu.config import MeshConfig

T = MeshConfig.AXIS_TENSOR


def _vit_rule(path, leaf) -> Optional[P]:
    name = keystr(path)
    is_kernel = "kernel" in name
    is_bias = "bias" in name
    if "qkv" in name:
        # kernel (d, 3, heads, head_dim); bias (3, heads, head_dim)
        if is_kernel:
            return P(None, None, T, None)
        if is_bias:
            return P(None, T, None)
    if "attn" in name and ("'out'" in name or "out" in name.split("'")):
        # kernel (heads, head_dim, d) row-parallel; bias (d,) replicated
        if is_kernel:
            return P(T, None, None)
        return None
    if "fc_in" in name:
        # kernel (d, mlp) column-parallel; bias (mlp,)
        if is_kernel:
            return P(None, T)
        if is_bias:
            return P(T)
    if "fc_out" in name:
        # kernel (mlp, d) row-parallel; bias replicated
        if is_kernel:
            return P(T, None)
        return None
    return None


def _vit_pipe_rule(path, leaf) -> Optional[P]:
    """Pipelined ViT: block-stack leaves carry a leading depth dimension
    sharded over 'pipe' (each device holds its stage's contiguous blocks —
    depth-contiguous sharding coincides with stack_stages' (stages,
    depth/stages) reshape); embed/head replicated over 'pipe'.

    TP composes by suffix: the inner dims of each stacked block leaf take
    the plain ViT Megatron spec. The pipeline shard_map is manual over
    'pipe'/'data' only (parallel/pipeline.py axis_names), so 'tensor'
    stays a GSPMD-automatic axis inside the stage body and XLA inserts
    the row-parallel all-reduces there, exactly as in the unpipelined
    model."""
    name = keystr(path)
    if "'blocks'" in name:
        inner = _vit_rule(path, leaf)
        if inner is None:
            return P(MeshConfig.AXIS_PIPE)
        return P(MeshConfig.AXIS_PIPE, *inner)
    return None


def _moe_rule(dense_rule: Callable) -> Callable:
    """Wrap a dense rule with the MoE leaves: stacked expert weights
    shard their leading E dim over 'expert'; router replicated. One
    definition serves ViT-MoE and the MoE LM — the param naming
    (ops/moe.py) is shared, so the sharding must be too."""

    def rule(path, leaf) -> Optional[P]:
        name = keystr(path)
        if "expert_" in name:
            return P(MeshConfig.AXIS_EXPERT)
        if "router" in name:
            return None
        return dense_rule(path, leaf)

    return rule


_vit_moe_rule = _moe_rule(_vit_rule)


def _lm_rule(path, leaf) -> Optional[P]:
    """Decoder LM: Megatron embedding/vocab sharding on top of the block
    rules (the block param names are the ViT ones — models/lm.py reuses
    EncoderBlock). tok_embed (vocab, d) shards the vocab rows; lm_head
    (d, vocab) is column-parallel over the vocab; pos_embed replicated."""
    name = keystr(path)
    if "tok_embed" in name:
        return P(T, None) if "embedding" in name else None
    if "lm_head" in name:
        # bias-free by construction (GPT-2 convention, models/lm.py) —
        # only the (d, vocab) kernel exists
        return P(None, T)
    if "pos_embed" in name:
        return None
    return _vit_rule(path, leaf)


_lm_moe_rule = _moe_rule(_lm_rule)


def _lm_pipe_rule(path, leaf) -> Optional[P]:
    """Pipelined LM: stacked causal blocks shard like the pipelined ViT
    (stage dim over 'pipe' + Megatron inner dims over 'tensor'); the
    out-of-pipeline embed/head take the dense LM's vocab sharding."""
    name = keystr(path)
    if "'blocks'" in name:
        return _vit_pipe_rule(path, leaf)  # same stacked-block layout
    return _lm_rule(path, leaf)


_RULES: dict = {
    "vit": _vit_rule,
    "vit_tiny": _vit_rule,
    "vit_base": _vit_rule,
    "vit_tiny_pipe": _vit_pipe_rule,
    "vit_tiny_moe": _vit_moe_rule,
    "lm_tiny": _lm_rule,
    "lm_base": _lm_rule,
    "lm_moe": _lm_moe_rule,
    "lm_pipe": _lm_pipe_rule,
}


def param_sharding_rules(model_name: str) -> Optional[Callable]:
    """Return rules(path, leaf) -> PartitionSpec | None for a model family.

    None (no model parallelism — e.g. the conv families) means fully
    replicated parameters, the reference's DDP contract.
    """
    return _RULES.get(model_name.lower())
