"""Device mesh construction and sharding helpers.

The mesh replaces the reference's rank/world/env bookkeeping
(ddp_main.py:60-73): axes ("data", "seq", "tensor") carry data, sequence,
and tensor parallelism. Gradient synchronization is not a wrapper (the DDP
reducer, ddp_main.py:121-123) but a consequence of shardings: batch sharded
over "data" + params replicated ⇒ XLA inserts the gradient all-reduce over
ICI/DCN during backward, overlapped by the latency-hiding scheduler.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_practice_tpu.config import MeshConfig


def build_mesh(
    config: Optional[MeshConfig] = None, devices=None
) -> Mesh:
    """Build a Mesh over all (or given) devices with axes (data, seq, tensor)."""
    config = config or MeshConfig()
    devices = list(devices) if devices is not None else jax.devices()
    if config.data != -1:
        # explicit mesh smaller than the host's device count: use a subset
        want = (
            config.data * config.seq * config.tensor
            * config.pipe * config.expert
        )
        if want < len(devices):
            devices = devices[:want]
    shape = config.resolve(len(devices))
    try:
        dmesh = mesh_utils.create_device_mesh(
            shape, devices=np.asarray(devices)
        )
    except (ValueError, AssertionError):
        dmesh = np.asarray(devices).reshape(shape)
    return Mesh(dmesh, config.axis_names)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, *, seq_dim: Optional[int] = None) -> NamedSharding:
    """Sharding for a batch array: leading dim over 'data' (and, when
    seq_dim is given, that dim over 'seq' — sequence parallelism)."""
    if seq_dim is None:
        return NamedSharding(mesh, P(MeshConfig.AXIS_DATA))
    spec = [None] * (seq_dim + 1)
    spec[0] = MeshConfig.AXIS_DATA
    spec[seq_dim] = MeshConfig.AXIS_SEQ
    return NamedSharding(mesh, P(*spec))


def shard_state(state, mesh: Mesh, rules=None):
    """Build a sharding pytree for a train state.

    Parameters (and their optimizer-state mirrors, which share leaf shapes)
    follow the tensor-parallel `rules` when given; everything else is
    replicated — the data-parallel contract of the reference (full replica
    per device, ddp_main.py:117-123).
    """
    rep = replicated(mesh)

    if rules is None:
        return jax.tree.map(lambda _: rep, state)

    def leaf_sharding(path, leaf):
        spec = rules(path, leaf)
        return NamedSharding(mesh, spec) if spec is not None else rep

    return jax.tree_util.tree_map_with_path(leaf_sharding, state)
