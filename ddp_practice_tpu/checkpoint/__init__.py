"""Checkpointing: crash-safe save AND restore (the reference only saves).

Reference contract: rank-0-only `torch.save({"model": ..., "scaler": ...})`
once at end of training (origin_main.py:113, ddp_main.py:165-169); no load
path exists (SURVEY §2.5). Here: the full train-state pytree plus a
manifest carrying step count and the precision-policy name (the slot where
the reference kept GradScaler state — with bf16 there is no scaler, but the
schema keeps the field for continuity), and `restore` rebuilds a sharded
state on any mesh.

Crash safety (the load-bearing property for train/elastic.py — a torn save
at exactly the moment recovery matters would otherwise destroy the only
good checkpoint):

- each save goes to `<dir>/step_<N>/`, written first into a `tmp.` prefix
  and atomically `os.rename`d into place (manifest.json is written last
  inside the temp dir, so a complete `step_*/manifest.json` implies a
  complete checkpoint);
- previous checkpoints are retained (`keep_last`, default 3) and pruned
  oldest-first only after the new one is complete;
- `restore` picks the newest *complete* step dir, ignoring temp debris;
  "complete" means the manifest PARSES — a manifest truncated mid-write
  (torn legacy-layout copy, power loss inside the json dump) makes the
  restore fall back to the previous complete checkpoint instead of
  dying on the corrupt one.

Multi-host: EVERY process calls save() (the barriers are collective).
Leaves whose shards span hosts (FSDP/TP state) are NOT gathered — each
process writes its own addressable shards (replica 0 only, so exactly one
copy of each region lands on disk) to `shards.<proc>.npz` with an index
sidecar, and process 0 writes the dense leaves + manifest last. That
keeps host memory and network traffic O(local shards) per save instead of
O(model) per HOST that a process_allgather costs — the difference between
a demo and a checkpoint path that scales with FSDP. The directory must be
shared storage (NFS/GCS-style), the standard contract for distributed
checkpointing. restore() reassembles the full arrays from the shard files
under any process count — including a single host reading a multi-host
checkpoint — and re-shards onto whatever mesh the target dictates.

Format: one .npz of flattened dense leaves keyed by pytree path +
shards.<p>.npz/json for cross-host leaves + manifest.json. Self-contained
(no orbax API surface). The single-file layout of early development
(leaves.npz directly in `directory`) still restores.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, List, Optional

import jax
import numpy as np
from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten

_LEAVES = "leaves.npz"
_MANIFEST = "manifest.json"
_STEP_RE = re.compile(r"^step_(\d+)$")
_SCHEMA_VERSION = 2


def _leaf_to_host(leaf) -> np.ndarray:
    """Bring a fully-addressable leaf to host memory. Cross-host leaves
    never come through here — they take the per-process shard-file path
    (save() splits them out; no full-leaf gather exists in this module)."""
    return np.asarray(jax.device_get(leaf))


def _slices_to_index(slices, shape):
    """Serialize a Shard.index (tuple of slices) as [[start, stop], ...]."""
    out = []
    for sl, dim in zip(slices, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _local_shard_files(sharded):
    """(arrays, index) for THIS process's replica-0 shards of the given
    {leaf_i: jax.Array} map — each cross-host region is written by exactly
    one process, no duplication, no gather."""
    arrays, index = {}, []
    for i, leaf in sharded.items():
        for k, s in enumerate(leaf.addressable_shards):
            if s.replica_id != 0:
                continue
            key = f"leaf_{i}.s{k}"
            arrays[key] = np.asarray(s.data)
            index.append({
                "leaf": i,
                "key": key,
                "index": _slices_to_index(s.index, leaf.shape),
            })
    return arrays, index


def _complete_steps(directory: str) -> List[int]:
    """Step numbers of complete checkpoints, ascending."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m and os.path.exists(os.path.join(directory, name, _MANIFEST)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def _manifest_ok(ckpt_dir: str) -> bool:
    """True when the manifest PARSES, not merely exists.

    Rename makes a whole-dir publish atomic, but the manifest byte
    stream itself is not: a power loss mid-`json.dump` (or a torn copy
    of an older single-file layout) leaves a manifest that exists and
    parse-fails — presence alone would select it and restore would die
    on the ONLY checkpoint it was willing to look at. A corrupt newest
    manifest must instead fall back to the previous complete checkpoint
    (losing one interval of work beats losing the run)."""
    try:
        with open(os.path.join(ckpt_dir, _MANIFEST)) as f:
            json.load(f)
        return True
    except (OSError, ValueError, UnicodeDecodeError):
        # ValueError covers json.JSONDecodeError (its subclass)
        return False


def _resolve(directory: str) -> Optional[str]:
    """Directory actually holding leaves.npz/manifest.json, or None.

    step_N dirs win over a legacy root-level checkpoint: any step_N was
    written after the legacy file (this writer only produces step dirs),
    so preferring legacy would silently resume pre-upgrade state.
    Newest first, but a step whose manifest is corrupt/unreadable
    (_manifest_ok) is SKIPPED, not fatal — older complete checkpoints
    are still perfectly good recovery points.

    Last resort: a *complete* (manifest parses) dir under a temp or
    rename-aside name. A crash in the same-step re-save window can leave
    the only complete copies as tmp.step_N.*/step_N.old.* — both written
    with manifest last, so completeness still implies integrity — and
    refusing them would strand a recoverable run with no checkpoint."""
    for step in reversed(_complete_steps(directory)):
        cand = os.path.join(directory, f"step_{step}")
        if _manifest_ok(cand):
            return cand
    if os.path.exists(os.path.join(directory, _LEAVES)) \
            and _manifest_ok(directory):
        return directory  # legacy single-checkpoint layout
    best, best_step = None, -1
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            if not (name.startswith("tmp.step_") or ".old." in name):
                continue
            m = re.search(r"step_(\d+)", name)
            if m and int(m.group(1)) > best_step \
                    and _manifest_ok(os.path.join(directory, name)):
                best, best_step = name, int(m.group(1))
    return os.path.join(directory, best) if best else None


def save(
    directory: str,
    state: Any,
    *,
    extra: Optional[dict] = None,
    step: Optional[int] = None,
    keep_last: int = 3,
) -> str:
    """Write a new checkpoint under `directory` (crash-safe, retained).

    ALL processes must call this (leaf gathering is collective); only
    process 0 touches the filesystem (the rank-0 gate of
    ddp_main.py:165-169). Returns the final checkpoint path.
    """
    extra, step = _normalize_step(extra, step)
    arrays, names, sharded = _gather(
        state, host_dense=jax.process_index() == 0
    )
    final = os.path.join(directory, f"step_{step}")
    if not sharded:
        if jax.process_index() == 0:
            _write(directory, arrays, names, extra, step, keep_last)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            # no process may return (and possibly restart+restore) before
            # the checkpoint is fully on disk
            multihost_utils.sync_global_devices(f"ckpt_save_{step}")
        return final

    # cross-host leaves: per-process shard writes into a SHARED temp dir
    # (deterministic name), manifest written last by process 0 after every
    # writer has finished — completeness still implies integrity
    from jax.experimental import multihost_utils

    pid = jax.process_index()
    tmp = os.path.join(directory, f"tmp.step_{step}.shared")
    if pid == 0:
        os.makedirs(directory, exist_ok=True)
        if os.path.isdir(tmp):
            # a crashed earlier save may have left this as the ONLY
            # complete checkpoint (_resolve's last resort accepts it) —
            # move it aside, never delete before the new one is durable
            # (_publish's debris sweep runs after the rename)
            os.rename(tmp, f"{tmp}.old.{os.getpid()}")
        os.makedirs(tmp)
    multihost_utils.sync_global_devices(f"ckpt_tmpdir_{step}")
    shard_arrays, shard_index = _local_shard_files(sharded)
    np.savez(os.path.join(tmp, f"shards.{pid}.npz"), **shard_arrays)
    with open(os.path.join(tmp, f"shards.{pid}.json"), "w") as f:
        json.dump(shard_index, f)
    multihost_utils.sync_global_devices(f"ckpt_shards_{step}")
    if pid == 0:
        sharded_meta = {
            str(i): {
                "shape": list(leaf.shape),
                "dtype": str(np.dtype(leaf.dtype)),
            }
            for i, leaf in sharded.items()
        }
        _serialize_into(tmp, arrays, names, extra, sharded_meta)
        _publish(directory, tmp, final, keep_last)
    multihost_utils.sync_global_devices(f"ckpt_save_{step}")
    return final


class AsyncSave:
    """Handle for a background checkpoint write (save_async).

    wait() joins the writer and returns the final path, re-raising any
    write error; done() polls."""

    def __init__(self, thread, path: str):
        self._thread = thread
        self._error: list = []
        self.path = path

    def wait(self) -> str:
        self._thread.join()
        if self._error:
            raise self._error[0]
        return self.path

    def done(self) -> bool:
        return not self._thread.is_alive()


def save_async(
    directory: str,
    state: Any,
    *,
    extra: Optional[dict] = None,
    step: Optional[int] = None,
    keep_last: int = 3,
) -> AsyncSave:
    """Like save(), but the serialization + atomic rename run on a
    background thread, so the train loop only pays the leaf gather (a
    device fence + D2H copy) and overlaps the disk write with the next
    steps. Crash safety is identical (same temp-dir + rename protocol).

    Single-process only: the multi-host save is a collective whose
    ordering must match across processes, so it stays synchronous —
    callers fall back to save() there (Trainer does).

    Do not overlap async saves to the same directory: the end-of-write
    debris sweep of one save may remove another's in-flight temp dir.
    wait() on the previous handle first (Trainer serializes this way).
    """
    if jax.process_count() > 1:
        raise ValueError(
            "save_async is single-process; multi-host saves are collective "
            "— use save()"
        )
    import threading

    extra, step = _normalize_step(extra, step)
    arrays, names, sharded = _gather(state)
    if sharded:
        # unreachable for process_count()==1 (every array is fully
        # addressable there), but a bare assert could be compiled out
        # under python -O and silently write a checkpoint with the
        # sharded leaves missing — fail loudly instead (advisor, round 3)
        raise ValueError(
            f"save_async got {len(sharded)} cross-host-sharded leaves; "
            "multi-host saves are collective — use save()"
        )
    final = os.path.join(directory, f"step_{step}")

    def _run():
        try:
            _write(directory, arrays, names, extra, step, keep_last)
        except BaseException as e:  # surfaced by wait()
            handle._error.append(e)

    thread = threading.Thread(target=_run, name=f"ckpt-write-{step}")
    handle = AsyncSave(thread, final)
    thread.start()
    return handle


def _normalize_step(extra, step):
    """One place decides the step dir number from extra/step (save and
    save_async must produce identical manifests)."""
    extra = dict(extra or {})
    if step is None:
        step = int(extra.get("step", 0))
    extra.setdefault("step", step)
    return extra, step


def _gather(state, *, host_dense: bool = True):
    """Flatten the state: fully-addressable leaves to host memory
    (arrays), cross-host leaves left on device for the per-process
    shard-file path (sharded: {leaf_i: jax.Array}).

    host_dense=False skips the D2H copies of the dense leaves — only
    process 0 ever writes them, so the other processes should not pay a
    device fence + transfer per save."""
    paths_and_leaves, _ = tree_flatten_with_path(state)
    arrays = {}
    names = []
    sharded = {}
    for i, (path, leaf) in enumerate(paths_and_leaves):
        names.append(keystr(path))
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            sharded[i] = leaf
        elif host_dense:
            arrays[f"leaf_{i}"] = _leaf_to_host(leaf)
    return arrays, names, sharded


def _serialize_into(tmp, arrays, names, extra, sharded_meta=None) -> None:
    """Write leaves.npz then manifest.json (LAST — its presence marks the
    checkpoint complete) into an existing temp dir. One implementation
    for the dense and sharded save paths, so the schema cannot drift."""
    np.savez(os.path.join(tmp, _LEAVES), **arrays)
    manifest = {
        "schema_version": _SCHEMA_VERSION,
        "paths": names,
        "extra": extra,
    }
    if sharded_meta:
        manifest["sharded_leaves"] = sharded_meta
    with open(os.path.join(tmp, _MANIFEST), "w") as f:
        json.dump(manifest, f, indent=2)


def _write(directory, arrays, names, extra, step, keep_last) -> str:
    """Serialize + atomically publish one checkpoint (host data only)."""
    final = os.path.join(directory, f"step_{step}")
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f"tmp.step_{step}.{os.getpid()}")
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    _serialize_into(tmp, arrays, names, extra)
    return _publish(directory, tmp, final, keep_last)


def _publish(directory, tmp, final, keep_last) -> str:
    """Atomically swing a complete temp dir into place, prune, sweep."""
    if os.path.isdir(final):
        # re-save at the same step (e.g. the end-of-fit save landing on
        # the last periodic save's step): move the old dir aside before
        # the swap so no crash instant leaves step_N deleted with the
        # replacement still under an ignored tmp. name
        old = f"{final}.old.{os.getpid()}"
        os.rename(final, old)
        os.rename(tmp, final)  # atomic on POSIX (same filesystem)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.rename(tmp, final)  # atomic on POSIX (same filesystem)
    # prune only after the new checkpoint is durable
    steps = _complete_steps(directory)
    for old in steps[:-keep_last] if keep_last > 0 else []:
        shutil.rmtree(
            os.path.join(directory, f"step_{old}"), ignore_errors=True
        )
    # sweep stale debris from crashed earlier saves
    for name in os.listdir(directory):
        if name.startswith("tmp.step_") or ".old." in name:
            shutil.rmtree(os.path.join(directory, name), ignore_errors=True)
    return final


def restore(directory: str, target: Any, *, shardings: Any = None) -> Any:
    """Rebuild `target`-structured state from the newest complete checkpoint.

    Leaves are matched by position with path-string verification (parameter
    renames across framework versions are rejected loudly, not silently
    misassigned). With `shardings` (a matching pytree of NamedSharding),
    leaves are placed sharded — so a checkpoint written on one mesh
    restores onto another (e.g. single-chip -> v4-8) — and the restore is
    STREAMING: shard-file leaves are read region-by-region into exactly
    the slices this process's devices need (O(local shards) host memory,
    the mirror of the per-process shard save — round 4), and dense leaves
    go to device one at a time, so peak host memory is one leaf, not the
    model. Without `shardings`, everything is assembled full on host (the
    single-host inspection/full-restore path).
    """
    src = _resolve(directory)
    if src is None:
        raise FileNotFoundError(f"no complete checkpoint under {directory!r}")
    data = np.load(os.path.join(src, _LEAVES))
    with open(os.path.join(src, _MANIFEST)) as f:
        manifest = json.load(f)
    sharded_meta = manifest.get("sharded_leaves") or {}
    paths_and_leaves, treedef = tree_flatten_with_path(target)
    if len(paths_and_leaves) != len(manifest["paths"]):
        raise ValueError(
            f"checkpoint has {len(manifest['paths'])} leaves; "
            f"target has {len(paths_and_leaves)}"
        )
    if shardings is not None:
        sh_flat, sh_treedef = jax.tree.flatten(shardings)
        if sh_treedef != treedef:
            # a same-count, differently-structured tree would otherwise
            # zip positionally and hand equal-shaped leaves each other's
            # shardings silently
            raise ValueError(
                f"shardings pytree structure {sh_treedef} does not match "
                f"the target's {treedef}"
            )
        shard_files = _open_shard_files(src) if sharded_meta else []
    else:
        assembled = _assemble_shards(src, manifest)

    leaves = []
    for i, (path, leaf) in enumerate(paths_and_leaves):
        want = keystr(path)
        got = manifest["paths"][i]
        if want != got:
            raise ValueError(f"checkpoint leaf {i} is {got!r}; target wants {want!r}")
        meta = sharded_meta.get(str(i))  # json keys are always strings
        host = None
        if meta is not None:
            ck_shape = tuple(meta["shape"])
        else:
            host = data[f"leaf_{i}"]  # read the zip member exactly once
            ck_shape = tuple(host.shape)
        want_shape = getattr(leaf, "shape", None)
        if want_shape is not None and ck_shape != tuple(want_shape):
            # e.g. generate.py --seq_len different from the training run:
            # fail here with the mismatch named, not deep inside flax
            raise ValueError(
                f"checkpoint leaf {want!r} has shape {ck_shape}; "
                f"target wants {tuple(want_shape)} — the checkpoint was "
                "written with a different model configuration"
            )
        dtype = getattr(leaf, "dtype", None)
        if shardings is not None:
            if meta is not None:
                arr = _restore_leaf_streamed(
                    i, meta, sh_flat[i], shard_files, dtype
                )
            else:
                if dtype is not None and host.dtype != dtype:
                    host = host.astype(dtype)
                arr = jax.device_put(host, sh_flat[i])
                host = None  # one dense leaf on host at a time
        else:
            arr = assembled[i] if meta is not None else host
            if dtype is not None and arr.dtype != dtype:
                arr = arr.astype(dtype)
        leaves.append(arr)
    return tree_unflatten(treedef, leaves)


def _open_shard_files(src: str):
    """[(index entries, lazy npz)] for every shards.<p> pair under src."""
    out = []
    for name in sorted(os.listdir(src)):
        if not (name.startswith("shards.") and name.endswith(".json")):
            continue
        with open(os.path.join(src, name)) as f:
            index = json.load(f)
        out.append((index, np.load(os.path.join(src, name[:-len("json")] + "npz"))))
    return out


def _restore_leaf_streamed(i, meta, sharding, shard_files, dtype):
    """Build one sharded jax.Array reading ONLY the regions this process's
    devices need: for each addressable device, a buffer of its shard shape
    is filled from the intersecting shard-file regions and placed
    immediately — no full-leaf host materialization (the save path's
    O(local shards) property, mirrored). Coverage of every device buffer
    is verified element-exactly, so a missing writer file fails loudly."""
    shape = tuple(meta["shape"])
    dtype = dtype or np.dtype(meta["dtype"])
    dev_map = sharding.addressable_devices_indices_map(shape)
    # pre-filter this leaf's entries and memoize decompressed members:
    # NpzFile re-reads the zip member on every access, and replicated or
    # re-meshed restores visit the same region from several devices
    entries = [
        (entry, shards)
        for index, shards in shard_files
        for entry in index
        if int(entry["leaf"]) == i
    ]
    pieces: dict = {}
    bufs = []
    for dev, idx in dev_map.items():
        # the same [start, stop) serialization the save path uses
        bounds = _slices_to_index(idx, shape)
        region = np.zeros([b - a for a, b in bounds], dtype)
        filled = 0
        for entry, shards in entries:
            inter = [
                (max(a, ea), min(b, eb))
                for (a, b), (ea, eb) in zip(bounds, entry["index"])
            ]
            if any(a >= b for a, b in inter):
                continue
            dst = tuple(
                slice(a - ra, b - ra)
                for (a, b), (ra, _) in zip(inter, bounds)
            )
            src_sl = tuple(
                slice(a - ea, b - ea)
                for (a, b), (ea, _) in zip(inter, entry["index"])
            )
            cache_key = (id(shards), entry["key"])
            if cache_key not in pieces:
                pieces[cache_key] = shards[entry["key"]]
            region[dst] = pieces[cache_key][src_sl].astype(dtype)
            filled += int(np.prod([b - a for a, b in inter]))
        want = int(np.prod(region.shape))
        if filled != want:
            raise ValueError(
                f"sharded leaf {i}: device {dev} needs {want} elements but "
                f"only {filled} are covered by shard files — shard files "
                "from some writer process are missing (incomplete or "
                "non-shared storage?)"
            )
        bufs.append(jax.device_put(region, dev))
    return jax.make_array_from_single_device_arrays(shape, sharding, bufs)


def _assemble_shards(src: str, manifest: dict) -> dict:
    """Reassemble cross-host leaves from the per-process shard files.

    Works under ANY process count — a single host restoring a multi-host
    checkpoint just reads every shards.<p>.npz it finds. Coverage is
    verified element-exactly (replica-0 shards partition each array), so
    a missing writer's file fails loudly instead of returning zeros."""
    meta = manifest.get("sharded_leaves") or {}
    if not meta:
        return {}
    out = {
        int(i): np.zeros(m["shape"], np.dtype(m["dtype"]))
        for i, m in meta.items()
    }
    filled = {int(i): 0 for i in meta}
    for index, shards in _open_shard_files(src):
        for entry in index:
            i = int(entry["leaf"])
            sl = tuple(slice(a, b) for a, b in entry["index"])
            out[i][sl] = shards[entry["key"]]
            filled[i] += int(np.prod([b - a for a, b in entry["index"]]))
    for i, m in meta.items():
        want = int(np.prod(m["shape"]))
        if filled[int(i)] != want:
            raise ValueError(
                f"sharded leaf {i} has {filled[int(i)]} of {want} elements "
                f"on disk under {src!r} — shard files from some writer "
                "process are missing (incomplete or non-shared storage?)"
            )
    return out


def latest_manifest(directory: str) -> Optional[dict]:
    src = _resolve(directory)
    if src is None:
        return None
    with open(os.path.join(src, _MANIFEST)) as f:
        return json.load(f)


def all_steps(directory: str) -> List[int]:
    """Steps of all retained complete checkpoints (ascending)."""
    return _complete_steps(directory)


def exists(directory: str) -> bool:
    return _resolve(directory) is not None
