"""Checkpointing: save AND restore (the reference only saves).

Reference contract: rank-0-only `torch.save({"model": ..., "scaler": ...})`
once at end of training (origin_main.py:113, ddp_main.py:165-169); no load
path exists (SURVEY §2.5). Here: process-0 writes the full train-state
pytree plus a manifest carrying step count and the precision-policy name
(the slot where the reference kept GradScaler state — with bf16 there is no
scaler, but the schema keeps the field for continuity), and `restore`
rebuilds a sharded state on any mesh.

Format: one .npz of flattened leaves keyed by pytree path + manifest.json.
Self-contained (no orbax API surface), multi-host-safe: only process 0
writes; every process reads.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import numpy as np
from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten

_LEAVES = "leaves.npz"
_MANIFEST = "manifest.json"


def save(directory: str, state: Any, *, extra: Optional[dict] = None) -> None:
    """Write state on process 0 (the rank-0 gate of ddp_main.py:165-169)."""
    if jax.process_index() != 0:
        return
    os.makedirs(directory, exist_ok=True)
    paths_and_leaves, treedef = tree_flatten_with_path(state)
    arrays = {}
    names = []
    for i, (path, leaf) in enumerate(paths_and_leaves):
        name = f"leaf_{i}"
        names.append(keystr(path))
        arrays[name] = np.asarray(jax.device_get(leaf))
    np.savez(os.path.join(directory, _LEAVES), **arrays)
    manifest = {"paths": names, "extra": extra or {}}
    with open(os.path.join(directory, _MANIFEST), "w") as f:
        json.dump(manifest, f, indent=2)


def restore(directory: str, target: Any, *, shardings: Any = None) -> Any:
    """Rebuild `target`-structured state from a checkpoint.

    Leaves are matched by position with path-string verification. With
    `shardings` (a matching pytree of NamedSharding), leaves are placed
    sharded — so a checkpoint written on one mesh restores onto another
    (e.g. single-chip -> v4-8).
    """
    data = np.load(os.path.join(directory, _LEAVES))
    with open(os.path.join(directory, _MANIFEST)) as f:
        manifest = json.load(f)
    paths_and_leaves, treedef = tree_flatten_with_path(target)
    if len(paths_and_leaves) != len(manifest["paths"]):
        raise ValueError(
            f"checkpoint has {len(manifest['paths'])} leaves; "
            f"target has {len(paths_and_leaves)}"
        )
    leaves = []
    for i, (path, leaf) in enumerate(paths_and_leaves):
        want = keystr(path)
        got = manifest["paths"][i]
        if want != got:
            raise ValueError(f"checkpoint leaf {i} is {got!r}; target wants {want!r}")
        arr = data[f"leaf_{i}"]
        if hasattr(leaf, "dtype") and arr.dtype != leaf.dtype:
            arr = arr.astype(leaf.dtype)
        leaves.append(arr)
    restored = tree_unflatten(treedef, leaves)
    if shardings is not None:
        restored = jax.tree.map(
            lambda x, s: jax.device_put(x, s), restored, shardings
        )
    return restored


def latest_manifest(directory: str) -> Optional[dict]:
    path = os.path.join(directory, _MANIFEST)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def exists(directory: str) -> bool:
    return os.path.exists(os.path.join(directory, _LEAVES)) and os.path.exists(
        os.path.join(directory, _MANIFEST)
    )
