"""Autoregressive inference: KV-cache prefill/decode + sampling.

The reference is a training-only demo — it saves a checkpoint and stops
(`origin_main.py:113`); there is no inference path anywhere in it. A
framework with a decoder LM family (models/lm.py) needs one, so this
module adds generation designed for the XLA compilation model:

- the ENTIRE generation — prompt prefill plus `max_new_tokens` decode
  steps — is one jittable pure function with static shapes: the K/V cache
  is pre-allocated in HBM at `prompt_len + max_new_tokens`, prefill writes
  the prompt's keys/values with one batched call (s = prompt length), and
  decoding is a `lax.scan` of single-token steps (s = 1);
- data-dependent stopping (EOS) is a done-mask folded through the scan,
  not a dynamic loop exit — sampled-after-done positions emit `pad_id`;
- sampling (greedy / temperature / top-k / nucleus top-p) happens
  on-device with an explicit PRNG key chain — logits arrive in the policy
  compute dtype (bf16 under the bf16 policy, models/lm.py) and
  `sample_logits` upcasts to fp32 before filtering — so a given
  (params, prompt, key) triple is reproducible across hosts and backends.

The cache lives in a flax "cache" variable collection (see
models/vit.py SelfAttention `decode=True`): each block holds
(b, total_len, heads, head_dim) key/value buffers plus a write cursor,
and the model tracks one top-level position cursor for the positional
embedding. `model.apply(..., mutable=["cache"])` threads it functionally
through the scan carry.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def cast_params_for_streaming(params: Any) -> Any:
    """fp32 leaves -> bf16 for inference-time param streaming.

    Training keeps fp32 master params, but decode re-reads the whole tree
    every token step, so streaming them as bf16 halves the HBM traffic.
    Under the bf16 compute policy the cast is BIT-IDENTICAL to applying
    the fp32 tree (every layer casts its kernel to the compute dtype
    before use — pinned in tests/test_generate.py); under an fp32 policy
    it changes numerics (weights round to bf16) and is not applied by
    default anywhere.
    """
    return jax.tree.map(
        lambda l: l.astype(jnp.bfloat16)
        if l.dtype == jnp.float32 else l,
        params,
    )


def make_cache(model, batch: int, total_len: int) -> Any:
    """Zero-initialized KV cache for `batch` sequences of `total_len`.

    Shapes come from `jax.eval_shape` over a decode-mode init — no FLOPs,
    no params materialized. Safe to call inside a jitted function (it is,
    in `make_generate_fn`).
    """
    shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((batch, total_len), jnp.int32),
            decode=True,
        )
    )["cache"]
    return jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), shapes)


def decode_apply(
    model,
    params,
    cache: Any,
    tokens: jnp.ndarray,
    *,
    attn_start=None,
    batch_stats: Any = None,
    page_table=None,
    kv_lengths=None,
) -> tuple:
    """One decode-mode model application: `(new_cache, logits)`.

    The single primitive both inference paths are built from — a prompt
    prefill is `decode_apply` with `tokens` spanning the prompt, a decode
    step is `decode_apply` with one token per sequence — so the one-shot
    generator below and the continuous-batching engine (serve/engine.py)
    share the exact apply (and therefore the exact logits): the cache
    collection threads through functionally, the write cursor advances by
    `tokens.shape[1]`, and `attn_start` masks left padding per sequence.

    `page_table` + `kv_lengths` switch the cache to the PAGED layout
    (serve/kv_pages.py): `cache` holds block pools instead of per-row
    buffers, each sequence writes/attends at its own slot-local position
    (kv_lengths), and there is no shared cursor — `attn_start` then masks
    in slot-local coordinates. `tokens` with s > 1 is a paged PREFILL:
    the s tokens land at positions kv_lengths[b] + [0, s), attending any
    already-resident prefix through the table (the prefix-cache
    admission path, serve/engine.py PagedEngine._prefix_prefill). An
    int8-cache model pools per-block scale pages alongside
    (models/vit.py).
    """
    variables = {"params": params, "cache": cache}
    if batch_stats is not None:
        variables["batch_stats"] = batch_stats
    kwargs = {}
    if page_table is not None:
        kwargs = {"page_table": page_table, "kv_lengths": kv_lengths}
    logits, mut = model.apply(
        variables,
        tokens,
        decode=True,
        mutable=["cache"],
        attn_start=attn_start,
        **kwargs,
    )
    return mut["cache"], logits


def sample_logits(
    logits: jnp.ndarray,
    key: Optional[jax.Array],
    *,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 0.0,
) -> jnp.ndarray:
    """Sample token ids (b,) from logits (b, vocab); any float dtype —
    upcast to fp32 here before temperature/filter math.

    temperature=0 is greedy argmax (no key needed). top_k keeps the k
    highest logits (clamped to the vocab size — asking for more than the
    vocab has is a no-op filter, not a lax.top_k shape error); top_p keeps
    the smallest prefix of the sorted distribution whose cumulative
    probability reaches p (the most likely token always survives). Both
    filters compose: k first, then p.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / temperature
    neg = jnp.asarray(-1e30, logits.dtype)
    top_k = min(top_k, logits.shape[-1])
    if top_k > 0:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p > 0.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        # exclusive cumulative prob: position i survives while the mass
        # BEFORE it is < p, so the argmax (mass 0 before it) always does
        cum = jnp.cumsum(probs, axis=-1) - probs
        keep = cum < top_p
        # threshold = smallest surviving logit
        thresh = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < thresh, neg, logits)
    return jax.random.categorical(key, logits, axis=-1)


def sample_logits_batch(
    logits: jnp.ndarray,
    keys: jnp.ndarray,
    *,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Per-ROW sampling over logits (b, vocab): temperature / top_k /
    top_p are traced (b,) arrays, not compile-time constants — the
    per-slot sampling path (serve/engine.py `per_slot_sampling`), where
    one jitted decode program serves a batch mixing greedy and sampled
    requests with arbitrary per-request params and never recompiles
    when they change.

    Row semantics match `sample_logits` exactly (pinned in
    tests/test_per_slot_sampling.py): temperature <= 0 is greedy
    argmax, top_k keeps the k highest logits (k <= 0 = off; ties at
    the kth value survive, as with lax.top_k), top_p keeps the
    smallest sorted prefix whose EXCLUSIVE cumulative probability is
    below p (p <= 0 = off); the filters compose k-then-p. The only
    difference is mechanism: a static k can call lax.top_k, a traced
    per-row k cannot, so the threshold comes from a descending sort —
    the same kth-largest VALUE either way. `keys` is (b, 2) uint32 raw
    key data, one independent chain per row; greedy rows ignore their
    draw (the chain still advances uniformly, so a request's stream
    never depends on its batchmates' params).
    """
    v = logits.shape[-1]
    logits32 = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits32, axis=-1)
    temperature = jnp.asarray(temperature, jnp.float32)
    top_k = jnp.asarray(top_k, jnp.int32)
    top_p = jnp.asarray(top_p, jnp.float32)
    is_greedy = temperature <= 0.0
    safe_t = jnp.where(is_greedy, 1.0, temperature)
    scaled = logits32 / safe_t[:, None]
    neg = jnp.asarray(-1e30, jnp.float32)
    k = jnp.clip(top_k, 0, v)
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        desc, jnp.maximum(k - 1, 0)[:, None], axis=-1
    )
    scaled = jnp.where((k[:, None] > 0) & (scaled < kth), neg, scaled)
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1) - probs
    keep = cum < top_p[:, None]
    thresh = jnp.min(
        jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True
    )
    scaled = jnp.where(
        (top_p[:, None] > 0.0) & (scaled < thresh), neg, scaled
    )
    # one categorical per row under its own key, called at the same
    # (1, vocab) shape as the per-request path so the drawn bits match
    # sample_logits bit-for-bit under the same sub-key
    sampled = jax.vmap(
        lambda kk, row: jax.random.categorical(kk, row[None], axis=-1)[0]
    )(keys, scaled)
    return jnp.where(is_greedy, greedy, sampled.astype(greedy.dtype))


def make_generate_fn(
    model,
    *,
    max_new_tokens: int,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 0.0,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
    batch_stats: Any = None,
) -> Callable[[Any, jnp.ndarray, Optional[jax.Array]], jnp.ndarray]:
    """Build `gen(params, prompt, key) -> tokens` for a decode-capable model.

    `prompt` is (b, prompt_len) int32 (uniform length per batch — byte-level
    prompts pad naturally by construction); the result is
    (b, prompt_len + max_new_tokens) with the prompt copied through. Wrap
    the returned function in `jax.jit` (the generate CLI and tests do); all
    sampling parameters are closed over as compile-time constants.

    `batch_stats`: the checkpoint's non-param state, REQUIRED for MoE
    models to route like they trained — the router's aux-free selection
    bias lives there (ops/moe.py); without it selection falls back to the
    raw gates. The tiny (E,)-sized leaves close over as jit constants.
    """

    def gen(params, prompt, key=None, prompt_lens=None):
        b, prompt_len = prompt.shape
        if prompt_len == 0:
            raise ValueError("prompt must contain at least one token")
        total = prompt_len + max_new_tokens
        if total > model.max_len:
            raise ValueError(
                f"prompt {prompt_len} + max_new_tokens {max_new_tokens} "
                f"exceeds model max_len {model.max_len}"
            )
        if temperature != 0.0 and key is None:
            raise ValueError("sampling (temperature != 0) needs a PRNG key")
        # variable-length batching: prompts arrive LEFT-padded (real tokens
        # right-aligned, pad_left_prompts builds this layout), so every
        # sequence's last prompt token sits at the same index and the
        # decode scan needs no per-sequence cursors; attn_start masks the
        # left padding out of every attention. RoPE-only (models/lm.py).
        attn_start = None
        if prompt_lens is not None:
            # lengths are traced under jit, so out-of-range values can't
            # raise here; clamp to [1, prompt_len] instead — a negative
            # start would silently attend the padding, a start past the
            # last prompt slot would leave query rows with no valid keys
            lens = jnp.clip(
                jnp.asarray(prompt_lens, jnp.int32), 1, prompt_len
            )
            attn_start = (prompt_len - lens).astype(jnp.int32)
        cache, logits = decode_apply(
            model, params, make_cache(model, b, total), prompt,
            attn_start=attn_start, batch_stats=batch_stats,
        )
        carry_key = key if key is not None else jax.random.PRNGKey(0)
        done = jnp.zeros((b,), bool)

        def step(carry, _):
            cache, last_logits, k, done = carry
            k, sub = jax.random.split(k)
            tok = sample_logits(
                last_logits, sub,
                temperature=temperature, top_k=top_k, top_p=top_p,
            ).astype(jnp.int32)
            tok = jnp.where(done, jnp.asarray(pad_id, jnp.int32), tok)
            if eos_id is not None:
                done = done | (tok == eos_id)
            cache, logits = decode_apply(
                model, params, cache, tok[:, None],
                attn_start=attn_start, batch_stats=batch_stats,
            )
            return (cache, logits[:, -1], k, done), tok

        (_, _, _, _), toks = lax.scan(
            step,
            (cache, logits[:, -1], carry_key, done),
            None,
            length=max_new_tokens,
        )
        return jnp.concatenate([prompt, toks.T], axis=1)

    return gen


def pad_left_prompts(prompts, pad_id: int = 0):
    """Batch variable-length token lists as a LEFT-padded array.

    Returns (tokens (b, max_len) int32, lengths (b,) int32) for
    `gen(params, tokens, key, prompt_lens=lengths)` — real tokens are
    right-aligned so all sequences share the decode cursor, and the
    returned lengths drive the attention mask over the padding.
    """
    lens = np.asarray([len(p) for p in prompts], np.int32)
    if (lens == 0).any():
        raise ValueError("every prompt must contain at least one token")
    width = int(lens.max())
    out = np.full((len(prompts), width), pad_id, np.int32)
    for i, p in enumerate(prompts):
        out[i, width - len(p):] = np.asarray(p, np.int32)
    return jnp.asarray(out), jnp.asarray(lens)


def encode_bytes(text: str) -> np.ndarray:
    """str -> (1, len) int32 byte tokens (the byte-level LM vocabulary)."""
    raw = np.frombuffer(text.encode("utf-8"), dtype=np.uint8)
    return raw.astype(np.int32)[None, :]


def decode_bytes(tokens) -> str:
    """(len,) byte tokens -> str (invalid UTF-8 replaced, not raised)."""
    arr = np.asarray(tokens).astype(np.uint8)
    return arr.tobytes().decode("utf-8", errors="replace")
