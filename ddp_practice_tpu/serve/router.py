"""Fault-tolerant router over N SlotEngine replicas.

One SlotEngine is one chip's batch; heavy traffic needs a fleet. This
router is the serving mirror of train/elastic.py: the training side
fails fast (watchdog) and recovers by checkpoint; the serving side
fails fast (circuit breaker, serve/health.py) and recovers by REQUEST
MIGRATION — a dead replica's in-flight requests are re-admitted on a
surviving replica as `prompt + tokens-generated-so-far`, a fresh
prefill that is token-identical under greedy decoding (the tokens
already streamed to the host were sampled from finite logits; decoding
is a pure function of the token prefix).

Dispatch is least-loaded, driven by the per-replica serve/metrics.py
gauges (queue depth + slot occupancy), preferring HEALTHY replicas over
DEGRADED ones. Failures are answered in layers:

- one bad completion (status "error": non-finite logits, transient
  admission failure) → bounded retry budget with exponential backoff +
  jitter (utils/backoff.py), on whichever replica is then least loaded;
- consecutive failures → breaker trips, replica goes DEAD, in-flight
  work migrates, half-open probes with backoff decide when it returns;
- fleet overload OR SLO burn → brown-out: when fleet pressure
  ((active + queued) / total slots) crosses `brownout_on`, or an
  attached SLO watchdog (serve/slo.py) has a burn-rate alert active —
  pressure is a proxy; a burning TTFT/error-rate SLO is the measured
  thing it stands for — low-priority requests (Request.priority >=
  shed_priority) are shed at the door AND out of replica queues, and
  new admissions get their `max_new_tokens` capped (degraded answers
  beat no answers); both revert only when pressure falls below
  `brownout_off` AND no SLO alert is active (hysteresis on both
  triggers, so the mode doesn't flap).

Every request ends in a defined terminal status — "eos"/"length" (ok),
"timeout" (deadline), "shed" (backpressure/brown-out), "rejected"
(malformed), or "error" (retry budget exhausted) — the chaos tests'
none-lost invariant. Time is injected (the schedulers' clock), so a
FaultPlan replay on FakeClock replicas is bit-for-bit deterministic.

Tracing (utils/trace.py, optional): the router stamps each request's
trace_id ONCE at intake and passes it through every retry/failover
re-admission, so a crash-migrated request's spans on the survivor join
the original timeline — the linkage the chaos tests assert. The router's
own lane (pid ROUTER_PID) records dispatch / retry / failover /
brown-out instants; per-replica spans come from the schedulers/engines.
Final completions carry a merged flight record: per-phase time summed
across attempts, stall_s = latency not spent on any replica (parked in
the retry heap, dead-replica gaps), plus retry/failover counts.
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional, Sequence

from ddp_practice_tpu.serve.engine import EngineConfig, SlotEngine
from ddp_practice_tpu.serve.faults import FaultPlan, ReplicaCrashed
from ddp_practice_tpu.serve.health import (
    BreakerConfig,
    HealthState,
    ReplicaHealth,
)
from ddp_practice_tpu.serve.metrics import RouterMetrics, ServeMetrics
from ddp_practice_tpu.serve.scheduler import (
    Completion,
    MonotonicClock,
    Request,
    Scheduler,
)
from ddp_practice_tpu.utils.backoff import backoff_delay
from ddp_practice_tpu.utils.metrics import MetricsRegistry
from ddp_practice_tpu.utils.trace import (
    ROUTER_PID,
    TraceSampler,
    label_replica,
    label_router,
)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    # ---- retry budget (per request, for "error" completions)
    max_retries: int = 2
    retry_base_s: float = 0.02
    retry_factor: float = 2.0
    retry_max_s: float = 1.0
    retry_jitter: float = 0.5
    # stamped as Request.deadline when the client set none (None = no
    # per-request timeout)
    request_timeout_s: Optional[float] = None
    # ---- circuit breaker (consecutive "error"s; crashes trip instantly)
    trip_after: int = 3
    probe_base_s: float = 0.05
    probe_factor: float = 2.0
    probe_max_s: float = 5.0
    probe_jitter: float = 0.0
    # ---- brown-out (fleet pressure = (active + queued) / total slots)
    brownout_on: float = 1.5
    brownout_off: float = 0.75
    brownout_max_new: int = 16
    # priority classes >= this are shed while browned out (0 =
    # interactive traffic, never brown-out shed)
    shed_priority: int = 1
    # jitter seed root: per-request retry jitter folds in the rid, per-
    # replica probe jitter folds in the replica id — deterministic replay,
    # de-synchronized fleet
    seed: int = 0
    # ---- streaming delivery: expose a per-request TokenStream fed from
    # the replicas' TokenChunks (scheduler.py), with the exactly-once /
    # resume contract. False = end-of-request delivery only (the
    # overhead bench's control arm; chunks from replicas are drained
    # and discarded so handle state stays bounded).
    streaming: bool = True
    # ---- cache-aware dispatch: score HEALTHY replicas by expected
    # prefix-hit tokens from their published radix digests (affinity.py)
    # and dispatch by affinity minus a load penalty. Degrades to the
    # least-loaded sort wherever digests are absent/cold, so fleets of
    # non-paged engines behave byte-identically to cache_aware=False.
    cache_aware: bool = True
    # ---- weighted-fair service (serve/fairshare.py): when on,
    # make_router threads one VirtualTokenCounter through every
    # scheduler — queue heads go to the least-served tenant instead of
    # strict FIFO. Off (default) no VTC exists anywhere on the path, so
    # scheduling is byte-identical to the pre-fairness router.
    fair: bool = False


@dataclasses.dataclass
class _Tracked:
    """Router-side lifecycle of one client request across attempts."""

    req: Request
    budget: int                 # max_new_tokens after any brown-out cap
    prefix: List[int] = dataclasses.field(default_factory=list)
    first_token_time: Optional[float] = None
    retries: int = 0            # error retries consumed (bounded)
    failovers: int = 0          # crash migrations (not budget-bounded)
    done: bool = False
    # flight-record phase sums across attempts (sub-completion flights
    # accumulate here; _finalize derives stall_s as the residual)
    queue_s: float = 0.0
    prefill_s: float = 0.0
    decode_s: float = 0.0
    # speculative-decoding tallies summed the same way: a failover
    # mid-request keeps the dead attempt's drafted/accepted counts, so
    # the merged accept rate reflects the whole request
    spec_drafted: int = 0
    spec_accepted: int = 0
    # streaming splice point: len(prefix) at the CURRENT dispatch — a
    # chunk's attempt-local `start` plus this base is its absolute
    # offset in the client's output (the dedup key after failover)
    dispatch_base: int = 0
    # how the LAST dispatch picked its replica ("affinity" | "load" |
    # "fallback") and the prefix tokens the replicas actually served
    # from cache, summed across attempts — both surface in the flight
    # record so a trace can say WHY a request landed where it did
    route: Optional[str] = None
    prefix_hit_tokens: int = 0


@dataclasses.dataclass
class StreamEvent:
    """One edge on a TokenStream, in consumer order.

    `kind` is ``tokens`` (new output, never re-delivered), ``resumed``
    (a failover/retry splice happened HERE — the marker the exactly-once
    contract emits instead of duplicate or missing tokens), or ``end``
    (terminal, carries the request's final status — a brown-out shed
    mid-stream ends the stream with status "shed", never silence).
    `seq` is contiguous per stream from 0; `start` is the absolute
    token offset of `tokens[0]` in the client's output."""

    kind: str
    seq: int
    trace_id: Optional[str]
    t: float
    start: int = 0
    tokens: List[int] = dataclasses.field(default_factory=list)
    status: Optional[str] = None
    attrs: Optional[dict] = None


class TokenStream:
    """Per-request consumer stream with the exactly-once contract.

    The router appends StreamEvents as replica TokenChunks arrive;
    `delivered` counts absolute tokens handed to the consumer, and any
    chunk tokens at offsets below it are suppressed (counted in
    `suppressed`) — that is how a failover's re-decode of the salvaged
    prefix never reaches the consumer twice. `gaps` counts offsets
    that were skipped forward over (the chaos pin asserts 0: chunks
    and the salvage point ride the same worker frame, so the resume
    cursor can never outrun delivery). `resume_gap_s` sums the time
    the stream sat between a resume marker and its next token — the
    stall the flight record attributes to failover."""

    def __init__(self, rid: int, trace_id: Optional[str]) -> None:
        self.rid = rid
        self.trace_id = trace_id
        self.events: List[StreamEvent] = []
        self.delivered = 0
        self.closed = False
        self.status: Optional[str] = None
        # replica tokens suppressed by the dedup cursor (failover
        # re-decode of the salvaged prefix lands here — EXPECTED under
        # chaos; consumer-visible duplicates are structurally impossible
        # and re-checked from the event log by bench/check_stream)
        self.suppressed = 0
        self.gaps = 0
        self.resume_gap_s = 0.0
        self._resumed_at: Optional[float] = None

    @property
    def next_seq(self) -> int:
        return len(self.events)

    def tokens(self) -> List[int]:
        """The consumer's view: every delivered token, concatenated."""
        out: List[int] = []
        for ev in self.events:
            out.extend(ev.tokens)
        return out


class ReplicaHandle:
    """One IN-PROCESS replica: engine + scheduler + gauges + health, as
    the router sees it. The scheduler/engine pair is exactly the PR-1
    single-replica serving stack — the router composes, it does not
    reimplement.

    This class also DEFINES the narrow replica interface the Router
    drives — `submit` / `step` / `poll` / `evacuate` / `shed_queued`
    (the Scheduler.submit / completions-watermark seam) plus the
    load/capacity observables (`load`, `has_queue_space`, `max_slots`,
    `queue_len`, `active`, `fits_prompt`) and lifecycle edges
    (`probe_ok`, `restart`, `warmup`, `compile_stats`). The in-process
    implementation is direct calls; serve/supervisor.py's
    RemoteReplicaHandle implements the SAME interface over the
    serve/rpc.py wire to a worker OS process — the router cannot tell
    them apart, which is the whole point of the seam."""

    def __init__(self, rid: int, scheduler: Scheduler,
                 breaker: BreakerConfig = BreakerConfig()) -> None:
        self.id = rid
        self.scheduler = scheduler
        self.engine: SlotEngine = scheduler.engine
        self.health = ReplicaHealth(breaker)
        self.consumed = 0  # completions watermark (survives restarts)
        self.chunks_consumed = 0  # TokenChunk watermark (same contract)

    # --------------- the seam: submit down, completions watermark up
    def submit(self, req: Request) -> None:
        """Hand one (sub-)request to the replica. A shed/reject lands
        as a completion in the next poll — never an exception."""
        self.scheduler.submit(req)

    def step(self) -> None:
        """Advance the replica one tick. May raise ReplicaCrashed. A
        remote replica self-steps; its step() is the heartbeat/poll."""
        self.scheduler.step()

    def poll(self) -> List[Completion]:
        """Completions since the watermark (consume-once)."""
        comps = self.scheduler.completions
        new, self.consumed = comps[self.consumed:], len(comps)
        return new

    def poll_chunks(self) -> List:
        """TokenChunks since the chunk watermark (consume-once) — the
        streaming twin of poll(). The list is append-only across
        restarts, so the watermark never replays."""
        chunks = self.scheduler.chunks
        new = chunks[self.chunks_consumed:]
        self.chunks_consumed = len(chunks)
        return new

    def evacuate(self) -> List[tuple]:
        """(request, tokens_so_far, ftt, phases) for everything this
        replica held — the failover harvest (Scheduler.evacuate)."""
        return self.scheduler.evacuate()

    def shed_queued(self, min_priority: int,
                    covers=None, tenants=None) -> List[int]:
        """Shed queued requests with priority >= min_priority (the
        brown-out lever); returns their rids. `covers` (tenant -> bool)
        narrows the shed to the burning tenants' work — a tenant-scoped
        brown-out must never pay a compliant tenant's requests for a
        hostile tenant's burn. `tenants` is the remote seam's
        serializable rendering of the same scope; the in-process handle
        has the exact predicate, so it is ignored here. The shed
        completions are consumed HERE
        (watermark advanced): the router finalizes from the returned
        rids, so replaying them from poll() would double-book — worse,
        the rid may have been reused by then."""
        shed = self.scheduler.shed_queued(
            lambda r: r.priority >= min_priority
            and (covers is None or covers(r.tenant))
        )
        self.consumed = len(self.scheduler.completions)
        return [r.rid for r in shed]

    # ------------------------------------------------- observables
    @property
    def load(self) -> float:
        """Least-loaded dispatch signal: queue depth + occupied slots,
        read from the replica's ServeMetrics gauges (the ROADMAP's
        'metrics gauges are the routing signals'); falls back to direct
        scheduler state when the replica carries no metrics."""
        m = self.scheduler.metrics
        slots = self.engine.config.max_slots
        if m is not None:
            return m.queue_depth.value + m.slot_occupancy.value * slots
        return len(self.scheduler.queue) + self.engine.num_active

    @property
    def has_queue_space(self) -> bool:
        return len(self.scheduler.queue) < self.scheduler.max_queue

    @property
    def max_slots(self) -> int:
        return self.engine.config.max_slots

    @property
    def queue_len(self) -> int:
        return len(self.scheduler.queue)

    @property
    def active(self) -> int:
        return self.engine.num_active

    def fits_prompt(self, n_tokens: int) -> bool:
        """Can a prompt of n_tokens prefill here? Delegates to the
        engine's own feasibility probe (bucket-bounded, except the
        chunk-capable paged engine, which is capacity-bounded)."""
        probe = getattr(self.engine, "fits_prompt", None)
        if probe is not None:
            return probe(n_tokens)
        try:
            self.engine.bucket_for(n_tokens)
            return True
        except ValueError:
            return False

    @property
    def kv_summary(self) -> Optional[dict]:
        """KV/radix-cache summary + prefix digest, read straight off
        the engine — the in-process twin of the worker's `_kv_summary`
        heartbeat payload (same builder, affinity.kv_summary), so the
        router's affinity scorer works identically with and without
        the RPC seam. None for non-paged engines."""
        if getattr(self.engine, "radix", None) is None:
            return None
        if not hasattr(self, "_digest_pub"):
            from ddp_practice_tpu.serve.affinity import DigestPublisher
            self._digest_pub = DigestPublisher(self.engine.radix)
        from ddp_practice_tpu.serve.affinity import kv_summary
        return kv_summary(self.engine, self._digest_pub)

    # --------------------------------------------------- lifecycle
    def probe_ok(self, now: float) -> bool:
        """Half-open probe: is the replica reachable again? With an
        injected fault plan the answer is the plan's crash window; a
        replica that crashed for real (no injector) is assumed
        restartable — in-process, restart() rebuilds its device state."""
        inj = self.scheduler.fault_hook
        return inj is None or inj.alive(now)

    def restart(self) -> None:
        """Bring a probed-alive replica back: free every slot, rewind
        the pool clock. The scheduler's queue/running were already
        evacuated at death; its completions list (and our watermark)
        survive so no completion is double-consumed."""
        eng = self.engine
        for slot in list(eng.allocator.used_slots()):
            eng.release(slot)
        eng.reset_epoch()
        inj = self.scheduler.fault_hook
        if inj is not None:
            inj.revive()

    def warmup(self, widths: Optional[Sequence[int]] = None) -> None:
        """Compile this replica's programs outside any timed window
        (engine.warm_engine — the one recipe workers also use)."""
        from ddp_practice_tpu.serve.engine import warm_engine

        warm_engine(self.engine, widths)

    def compile_stats(self) -> dict:
        return self.engine.compile_stats()


class Router:
    """Least-loaded, health-checked dispatch over a replica fleet."""

    def __init__(self, schedulers: Sequence, *, clock=None,
                 config: RouterConfig = RouterConfig(),
                 metrics: Optional[RouterMetrics] = None,
                 tracer=None, slo=None, telemetry=None,
                 policy=None, vtc=None, ledger=None) -> None:
        """`schedulers` is the replica fleet: Scheduler objects (the
        in-process fleet — wrapped in ReplicaHandle here) and/or
        prebuilt handle objects implementing ReplicaHandle's replica
        interface (serve/supervisor.py RemoteReplicaHandle for worker
        OS processes). The router owns breaker POLICY either way: it
        (re)arms each handle's ReplicaHealth from its own config."""
        if not schedulers:
            raise ValueError("need at least one replica")
        self.clock = clock or getattr(schedulers[0], "clock", None)
        if self.clock is None:
            raise ValueError("pass clock= when building from handles")
        self.config = config
        self.metrics = metrics or RouterMetrics()
        self.tracer = tracer
        # optional serve/slo.py SLOWatchdog: fed every finalized
        # completion, evaluated once per tick; while it alerts, brown-out
        # engages regardless of fleet pressure (_update_brownout)
        self.slo = slo
        # optional utils/telemetry.py TelemetryExporter (or anything with
        # on_completion): streams one "flight" line per finalization and
        # feeds the /flight rolling window
        self.telemetry = telemetry
        # optional serve/fairshare.py pair: the VirtualTokenCounter the
        # schedulers charge (kept here for introspection — /tenants,
        # the bench's service report) and the TenantLedger fed one
        # on_completion per finalization (cost metering)
        self.vtc = vtc
        self.ledger = ledger
        # tenant scope of the CURRENT brown-out: None = global (pressure
        # trip, or an slo= without per-tenant queries); a tuple of
        # burning tenant names = shed/door-shed only their work
        self._brownout_scope = None
        if tracer is not None:
            label_router(tracer)
        self.handles = []
        for i, item in enumerate(schedulers):
            bcfg = BreakerConfig(
                trip_after=config.trip_after,
                probe_base_s=config.probe_base_s,
                probe_factor=config.probe_factor,
                probe_max_s=config.probe_max_s,
                probe_jitter=config.probe_jitter,
                seed=config.seed + i,
            )
            if isinstance(item, Scheduler):
                h = ReplicaHandle(i, item, bcfg)
            else:
                h = item
                h.health = ReplicaHealth(bcfg)
            self.handles.append(h)
        # dispatch policy seam: anything with order(cands, prompt, now)
        # -> (ordered, decisions, expected_hits) and forget(replica_id).
        # Default is digest-driven affinity (which itself degrades to
        # the least-loaded sort when no digest is usable); pass an
        # explicit policy= to override both.
        if policy is None:
            from ddp_practice_tpu.serve.affinity import (
                AffinityPolicy, LeastLoadedPolicy,
            )
            policy = (AffinityPolicy() if config.cache_aware
                      else LeastLoadedPolicy())
        self.policy = policy
        self.tracked: Dict[int, _Tracked] = {}
        self.completions: List[Completion] = []
        # streaming registry: rid -> TokenStream, created at intake,
        # closed by _finalize's typed end event. Closed streams stay
        # until the consumer takes them (the bench reads/clears per
        # rep) — the same accumulate-and-consume contract as
        # `completions`.
        self.streams: Dict[int, TokenStream] = {}
        self._streaming = config.streaming
        self.brownout = False
        self._pending = 0
        self._retry_q: List[tuple] = []  # (ready_at, seq, rid) heap
        self._retry_seq = 0
        # optional serve/autoscaler.py Autoscaler: evaluated once per
        # tick right after the SLO watchdog (its trip/resolve signals
        # are the autoscaler's inputs, so they must be fresh)
        self.autoscaler = None
        for h in self.handles:
            self.metrics.on_replica_state(h.id, h.health.state.value)

    # ------------------------------------------------- elastic membership
    def add_handle(self, h) -> None:
        """Join a NEW replica handle mid-run (autoscaler grow): armed
        with the same breaker policy __init__ applies, seeded by its
        stable slot id so probe jitter stays deterministic per slot."""
        bcfg = BreakerConfig(
            trip_after=self.config.trip_after,
            probe_base_s=self.config.probe_base_s,
            probe_factor=self.config.probe_factor,
            probe_max_s=self.config.probe_max_s,
            probe_jitter=self.config.probe_jitter,
            seed=self.config.seed + h.id,
        )
        h.health = ReplicaHealth(bcfg)
        self.handles.append(h)
        self.metrics.on_replica_state(h.id, h.health.state.value)

    def remove_handle(self, h) -> None:
        """Retire a replica handle mid-run (autoscaler shrink, after
        the drain). Anything it still holds is flushed and salvaged —
        chunks first so the delivery cursor is current, then leftovers
        re-dispatch on survivors — so removal can never strand a
        stream, even when the drain was cut short."""
        if h not in self.handles:
            return
        self._ingest_chunks(h)
        self._consume(h)
        for req, tokens, ftt, phases in h.evacuate():
            tr = self.tracked.get(req.rid)
            if tr is None or tr.done:
                continue
            tr.queue_s += phases["queue_s"]
            tr.prefill_s += phases["prefill_s"]
            tr.decode_s += phases["decode_s"]
            tr.prefix.extend(tokens)
            if tr.first_token_time is None:
                tr.first_token_time = ftt
            tr.failovers += 1
            self.metrics.failovers.inc()
            if not self._dispatch(tr):
                self._park_or_shed(tr)
        self.handles.remove(h)
        # drop its digest view: the slot is gone, and rendezvous
        # placement over the surviving ids re-homes its sticky families
        self.policy.forget(h.id)
        self.metrics.on_replica_state(h.id, "removed")

    # ------------------------------------------------------------ intake
    def submit(self, req: Request) -> bool:
        """Route one request; False = terminal at the door (shed or
        rejected — a completion exists either way, never silence)."""
        if req.arrival is None:
            req.arrival = self.clock.now()
        if req.rid in self.tracked:
            raise ValueError(f"duplicate rid {req.rid}")
        if req.trace_id is None:
            # stamped ONCE here: every retry/failover re-admission below
            # reuses it, so a migrated request is one timeline
            req.trace_id = f"r{req.rid}"
        if self.tracer is not None:
            # the head-sampling decision, stamped once with the trace_id
            # and propagated to every sub-request (and across the RPC
            # seam) — workers honor it instead of re-deciding
            req.sampled = self.tracer.begin_trace(req.trace_id,
                                                  req.sampled,
                                                  tenant=req.tenant)
        cfg = self.config
        if req.deadline is None and cfg.request_timeout_s is not None:
            req.deadline = req.arrival + cfg.request_timeout_s
        self.metrics.submitted.inc()
        budget = req.max_new_tokens
        if req.max_new_tokens < 1:
            # malformed beats browned-out: "rejected" is terminal advice
            # (never resubmit), "shed" invites a retry that can only fail
            self._finalize(self._track(req, budget), [], "rejected")
            return False
        if self.brownout and self._brownout_covers(req.tenant):
            if req.priority >= cfg.shed_priority:
                tr = self._track(req, budget)
                # slo_exempt: this shed IS the brown-out response — if
                # the watchdog counted it as an availability failure,
                # the controller would feed its own alert and never
                # disengage (positive-feedback latch)
                self._finalize(tr, [], "shed", slo_exempt=True)
                self.metrics.on_shed("brownout")
                return False
            budget = min(budget, cfg.brownout_max_new)
        tr = self._track(req, budget)
        if not self._dispatch(tr):
            self._finalize(tr, [], "shed")
            self.metrics.on_shed(
                "no_replica" if not self._alive() else "fleet_full"
            )
            return False
        return True

    def _track(self, req: Request, budget: int) -> _Tracked:
        tr = _Tracked(req=req, budget=budget)
        self.tracked[req.rid] = tr
        self._pending += 1
        if self._streaming:
            self.streams[req.rid] = TokenStream(req.rid, req.trace_id)
        return tr

    def stream(self, rid: int) -> Optional["TokenStream"]:
        """The consumer handle for one request's TokenStream (None when
        streaming is off or the rid was never submitted)."""
        return self.streams.get(rid)

    # --------------------------------------------------------- streaming
    def _stream_emit(self, st: TokenStream, kind: str, *, start: int = 0,
                     tokens=(), status: Optional[str] = None,
                     attrs: Optional[dict] = None) -> StreamEvent:
        now = self.clock.now()
        ev = StreamEvent(
            kind=kind, seq=st.next_seq, trace_id=st.trace_id, t=now,
            start=start, tokens=list(tokens), status=status, attrs=attrs,
        )
        st.events.append(ev)
        if kind == "resumed":
            if st._resumed_at is None:
                st._resumed_at = now
            if self.tracer is not None:
                # a resume splice is a tail keep-rule of its own: the
                # staged timeline promotes the moment the consumer saw
                # the seam, not at completion
                self.tracer.note_keep(st.trace_id, "resumed")
        elif st._resumed_at is not None:
            # the resume gap closes at the next consumer-visible edge
            # (first post-splice tokens, or the end if none ever came) —
            # the stall the flight record books as resume_gap_s
            st.resume_gap_s += now - st._resumed_at
            st._resumed_at = None
        if kind == "end":
            st.closed = True
            st.status = status
        emit = getattr(self.telemetry, "emit", None)
        if emit is not None:
            # one JSONL line per stream event: the offline exactly-once
            # audit trail (tools/check_stream.py) — contiguous seq per
            # trace_id, one terminal, original trace_id across failover
            emit("chunk", trace_id=st.trace_id, rid=st.rid, seq=ev.seq,
                 event=kind, start=ev.start, n=len(ev.tokens),
                 status=status)
        return ev

    def _stream_tokens(self, st: TokenStream, gstart: int,
                       toks: List[int]) -> None:
        """Feed replica chunk tokens at absolute offset `gstart` through
        the dedup cursor: only tokens past `delivered` reach the
        consumer, re-decoded salvage is suppressed, and a forward skip
        (structurally impossible — chunks and the salvage point share a
        frame) is counted as a gap rather than hidden."""
        if st.closed or not toks:
            return
        end = gstart + len(toks)
        if end <= st.delivered:
            st.suppressed += len(toks)
            return
        if gstart > st.delivered:
            st.gaps += gstart - st.delivered
            start = gstart
        else:
            st.suppressed += st.delivered - gstart
            start = st.delivered
        self._stream_emit(st, "tokens", start=start,
                          tokens=toks[start - gstart:])
        st.delivered = end

    def _ingest_chunks(self, h) -> None:
        """Drain one handle's TokenChunks into the streams. Runs even
        with streaming off (the handle's pending buffer must not grow
        unbounded); chunk-level `final` markers are scheduler-attempt
        scoped and deliberately ignored here — the ROUTER owns the
        terminal event (_finalize), because a sub-attempt's "error"
        final is a retry, not an ending, from the consumer's seat."""
        poll = getattr(h, "poll_chunks", None)
        if poll is None:
            return
        chunks = poll()
        if not self._streaming:
            return
        for ch in chunks:
            st = self.streams.get(ch.rid)
            if st is None or st.closed:
                continue
            tr = self.tracked.get(ch.rid)
            base = tr.dispatch_base if tr is not None else 0
            self._stream_tokens(st, base + ch.start, list(ch.tokens))

    # ---------------------------------------------------------- dispatch
    def _alive(self) -> List[ReplicaHandle]:
        return [h for h in self.handles if h.health.alive]

    def _dispatch(self, tr: _Tracked) -> bool:
        """Place (or re-place) a tracked request on the best replica.
        False = nowhere to put it right now (caller sheds or requeues)."""
        remaining = tr.budget - len(tr.prefix)
        if remaining <= 0:
            # a migrated request that already produced its whole budget
            self._finalize(tr, list(tr.prefix), "length",
                           tr.first_token_time)
            return True
        cands = [h for h in self._alive() if h.has_queue_space]
        if not cands:
            return False
        req = tr.req
        # the dispatch-policy seam: affinity scoring over the replicas'
        # published prefix digests when usable, the classic HEALTHY-
        # before-DEGRADED least-loaded sort otherwise (LeastLoadedPolicy
        # and the cold-digest fallback produce the identical order)
        cands, decisions, exp = self.policy.order(
            cands, req.prompt, self.clock.now()
        )
        for h in cands:
            if tr.prefix:
                if not h.fits_prompt(len(req.prompt) + len(tr.prefix)):
                    # prompt+prefix outgrew every prefill bucket (a long
                    # generation migrated late): drop the salvage and
                    # regenerate from the original prompt — it fit once,
                    # it fits again, and a deterministic decode
                    # reproduces the same tokens (the per-request PRNG
                    # chain restarts from the request seed). Recompute
                    # beats a lost request.
                    tr.prefix = []
                    remaining = tr.budget
            # the splice point for this attempt's chunks: attempt-local
            # chunk offsets + this base = absolute position in the
            # client's output (the stream dedup key)
            tr.dispatch_base = len(tr.prefix)
            sub = Request(
                rid=req.rid,
                # failover/retry resume: the tokens already produced ARE
                # the continuation — re-admitting prompt+prefix as a
                # fresh prefill reproduces the remaining tokens exactly
                # under greedy decoding
                prompt=list(req.prompt) + list(tr.prefix),
                max_new_tokens=remaining,
                deadline=req.deadline,
                seed=req.seed,
                arrival=req.arrival,
                priority=req.priority,
                # the ORIGINAL trace_id: the survivor's spans join the
                # migrated request's timeline (tests/test_trace.py)
                trace_id=req.trace_id,
                # a request that already retried / failed over IS the
                # anomaly tail sampling exists to keep: upgrade the
                # decision so the post-fault attempt records fully on
                # the worker (its pre-fault spans were tail-promoted by
                # the retry/failover markers)
                sampled=(True if (tr.retries or tr.failovers)
                         else req.sampled),
                tenant=req.tenant,
                # per-request sampling overrides ride every dispatch —
                # a failover re-admission must sample under the SAME
                # params or the spliced stream changes distribution
                temperature=req.temperature,
                top_k=req.top_k,
                top_p=req.top_p,
            )
            # stamp the dispatch time BEFORE the submit hop: a remote
            # worker can queue and even start prefill while the RPC is
            # still in flight, and a post-submit stamp would put the
            # dispatch instant AFTER the worker's spans — backwards
            # causality the fleet validator rightly rejects
            rec = self.tracer
            t_dispatch = (rec.now() if rec is not None and rec.enabled
                          else None)
            h.submit(sub)
            if getattr(h, "last_submit_refused", False):
                # a DRAINING worker refused at the door — typed and
                # certain, not a fault: try the next candidate instead
                # of writing the replica off (it is finishing in-flight
                # streams and will exit on its own)
                continue
            tr.route = decisions.get(h.id, "fallback")
            self.metrics.on_route(tr.route)
            if t_dispatch is not None:
                rec.record_instant(
                    "dispatch", t_dispatch, trace_id=req.trace_id,
                    pid=ROUTER_PID,
                    attrs={"replica": h.id,
                           "attempt": tr.retries + tr.failovers,
                           "salvaged": len(tr.prefix),
                           "route": tr.route,
                           "affinity_tokens": exp.get(h.id, 0)},
                )
            return True
        return False

    def _requeue(self, tr: _Tracked, delay_s: float) -> None:
        now = self.clock.now()
        deadline = tr.req.deadline
        if deadline is not None and now + delay_s > deadline:
            self._finalize(tr, list(tr.prefix), "timeout",
                           tr.first_token_time)
            return
        self._retry_seq += 1
        heapq.heappush(
            self._retry_q, (now + delay_s, self._retry_seq, tr.req.rid)
        )

    # ----------------------------------------------------------- the tick
    def step(self) -> List[Completion]:
        """One fleet tick: probe dead replicas, step the live ones
        (crashes trigger failover), consume completions (errors retry),
        drain due retries, update brown-out. Returns the client
        completions finalized during this tick."""
        before = len(self.completions)
        t_start = self.clock.now()
        self._probe_dead()
        for h in self.handles:
            if not h.health.alive:
                continue
            try:
                h.step()
            except ReplicaCrashed:
                self._kill(h)
        for h in self.handles:
            # chunks BEFORE completions: the dedup cursor must be
            # current when the terminal flush measures what is left
            self._ingest_chunks(h)
            self._consume(h)
        self._drain_retries()
        if self.slo is not None:
            self.slo.evaluate(self.clock.now())
        if self.autoscaler is not None:
            # after the SLO pass (burn rates fresh), before brown-out
            # (a grow this tick relieves the very pressure brown-out
            # would otherwise respond to)
            self.autoscaler.step(self.clock.now())
        self._update_brownout()
        if self.clock.now() == t_start:
            # nothing decoded this tick (fleet idle/dead): advance
            # virtual time anyway so retry backoffs and probe timers can
            # ever come due under FakeClock (no-op on the real clock)
            self.clock.tick()
        return self.completions[before:]

    def _probe_dead(self) -> None:
        now = self.clock.now()
        for h in self.handles:
            if h.health.alive or not h.health.probe_due(now):
                continue
            ok = h.probe_ok(now)
            h.health.on_probe(ok, now)
            if ok:
                h.restart()
                # the new incarnation's radix is cold: drop the digest
                # view so affinity can't route on the dead cache's
                # fingerprint (a stale digest costs a miss, never
                # correctness — but why pay the miss on purpose)
                self.policy.forget(h.id)
                if self.tracer is not None and self.tracer.enabled:
                    self.tracer.instant("replica_restart", pid=ROUTER_PID,
                                        replica=h.id)
            self.metrics.on_replica_state(h.id, h.health.state.value)

    def _kill(self, h: ReplicaHandle) -> None:
        """Replica death: trip the breaker and migrate everything it
        held — in-flight requests resume from their salvaged tokens."""
        now = self.clock.now()
        h.health.mark_dead(now)
        self.policy.forget(h.id)  # its warm cache died with it
        self.metrics.breaker_trips.inc()
        self.metrics.on_replica_state(h.id, h.health.state.value)
        rec = self.tracer
        if rec is not None and rec.enabled:
            rec.instant("replica_dead", pid=ROUTER_PID, replica=h.id)
        # flush chunks the dead replica already published: they rode
        # the same frames as the salvage point below, so after this the
        # delivery cursor and the resume cursor agree — the survivor's
        # re-decode dedups exactly, no duplicate and no gap
        self._ingest_chunks(h)
        for req, tokens, ftt, phases in h.evacuate():
            tr = self.tracked.get(req.rid)
            if tr is None or tr.done:
                continue
            # fold the dead attempt's on-replica time into the flight
            # record — no Completion will ever report it (evacuated
            # attempts don't finish), and without this the pre-crash
            # decode work would show up as stall_s
            tr.queue_s += phases["queue_s"]
            tr.prefill_s += phases["prefill_s"]
            tr.decode_s += phases["decode_s"]
            tr.prefix.extend(tokens)
            if tr.first_token_time is None:
                tr.first_token_time = ftt
            tr.failovers += 1
            self.metrics.failovers.inc()
            st = self.streams.get(req.rid)
            if st is not None and not st.closed:
                # the consumer sees a marker at the splice, never a
                # duplicate and never a hole — the exactly-once edge
                self._stream_emit(st, "resumed", attrs={
                    "reason": "failover", "from_replica": h.id,
                    "salvaged": len(tokens),
                })
            if rec is not None and rec.enabled:
                rec.instant("failover", trace_id=req.trace_id,
                            pid=ROUTER_PID, from_replica=h.id,
                            salvaged=len(tokens))
            if not self._dispatch(tr):
                self._park_or_shed(tr)

    def _consume(self, h: ReplicaHandle) -> None:
        now = self.clock.now()
        for c in h.poll():
            tr = self.tracked.get(c.rid)
            if tr is None or tr.done:
                continue  # e.g. brown-out sheds already finalized
            if c.flight is not None:
                # fold this attempt's on-replica phases into the merged
                # flight record (_finalize derives stall_s as residual)
                tr.queue_s += c.flight["queue_s"]
                tr.prefill_s += c.flight["prefill_s"]
                tr.decode_s += c.flight["decode_s"]
                tr.spec_drafted += c.flight.get("spec_drafted", 0)
                tr.spec_accepted += c.flight.get("spec_accepted", 0)
                tr.prefix_hit_tokens += c.flight.get(
                    "prefix_hit_tokens", 0)
            if tr.first_token_time is None and c.ttft is not None:
                tr.first_token_time = tr.req.arrival + c.ttft
            if c.status == "refused":
                # one-way submit reconciled as a DRAINING refusal
                # (supervisor._reconcile_confirm): typed and certain,
                # not a fault — re-dispatch on the next candidate
                # without a breaker mark or a retry charge, exactly
                # like the synchronous last_submit_refused skip
                if not self._dispatch(tr):
                    self._park_or_shed(tr)
                continue
            if c.status in ("eos", "length"):
                h.health.mark_success()
                self._finalize(tr, tr.prefix + c.tokens, c.status,
                               tr.first_token_time)
            elif c.status == "timeout":
                self._finalize(tr, tr.prefix + c.tokens, "timeout",
                               tr.first_token_time)
            elif c.status == "rejected":
                # malformed for this engine config (prompt over every
                # bucket / budget over the pool): identical replicas
                # would all reject it — not retryable
                self._finalize(tr, list(tr.prefix), "rejected")
            else:  # "error" (and the defensive "shed" path): retryable
                if h.health.mark_failure(now):
                    self._kill(h)  # trip: migrate the rest of its work
                self.metrics.on_replica_state(h.id, h.health.state.value)
                tr.prefix.extend(c.tokens)
                if tr.retries >= self.config.max_retries:
                    self._finalize(tr, list(tr.prefix), "error",
                                   tr.first_token_time)
                    continue
                tr.retries += 1
                self.metrics.retries.inc()
                st = self.streams.get(c.rid)
                if st is not None and not st.closed:
                    # an error retry is a resume point too: tokens
                    # already streamed stay delivered, the re-decode on
                    # the next replica dedups against them
                    self._stream_emit(st, "resumed", attrs={
                        "reason": "retry", "replica": h.id,
                        "salvaged": len(tr.prefix),
                    })
                cfg = self.config
                delay = backoff_delay(
                    tr.retries - 1, base_s=cfg.retry_base_s,
                    factor=cfg.retry_factor, max_s=cfg.retry_max_s,
                    jitter=cfg.retry_jitter, seed=cfg.seed + c.rid,
                )
                rec = self.tracer
                if rec is not None and rec.enabled:
                    rec.instant("retry", trace_id=tr.req.trace_id,
                                pid=ROUTER_PID, replica=h.id,
                                attempt=tr.retries, delay_s=delay)
                self._requeue(tr, delay)

    def _drain_retries(self) -> None:
        now = self.clock.now()
        while self._retry_q and self._retry_q[0][0] <= now:
            _, _, rid = heapq.heappop(self._retry_q)
            tr = self.tracked.get(rid)
            if tr is None or tr.done:
                continue
            deadline = tr.req.deadline
            if deadline is not None and now > deadline:
                self._finalize(tr, list(tr.prefix), "timeout",
                               tr.first_token_time)
                continue
            if not self._dispatch(tr):
                # still nowhere to go: shed or park, then stop draining
                # (the fleet state won't change within this tick)
                self._park_or_shed(tr)
                break

    def _park_or_shed(self, tr: _Tracked) -> None:
        """A request with nowhere to run: queues full on a live fleet is
        TRANSIENT (they drain as decode proceeds — park it for one
        backoff), but a fleet with no alive replica gets the same answer
        the front door gives (submit): an immediate terminal shed. The
        fast no keeps the none-lost invariant even when every replica is
        permanently dead — parking there would cycle the retry heap
        forever and hang run_until_idle / the bench loop."""
        if not self._alive():
            self._finalize(tr, list(tr.prefix), "shed")
            self.metrics.on_shed("no_replica")
        else:
            self._requeue(tr, self.config.retry_base_s)

    # --------------------------------------------------------- brown-out
    def _brownout_covers(self, tenant) -> bool:
        """Whether the active brown-out applies to `tenant`'s work.
        Global scope (pressure trip, or an slo= object without
        per-tenant queries) covers everyone; an SLO-scoped brown-out
        covers only the burning tenants — the compliant tenant keeps
        its full budget and its queue slots."""
        if self._brownout_scope is None:
            return True
        is_b = getattr(self.slo, "is_burning", None)
        if is_b is None:
            return True
        return bool(is_b(tenant))

    def _shed_brownout_queued(self, covers=None) -> None:
        """Shed low-priority WAITERS too, not just new arrivals — the
        queue backlog is exactly the overload being answered.
        (shed_queued consumes its own sub-completions — replaying
        them from poll() would double-book against whatever
        request is tracked under the rid by then.)

        Scoped sheds ride the seam twice: `covers` (the exact
        registry-backed predicate, overflow fold included) for
        in-process handles, and the raw scope NAMES for remote ones —
        a callable cannot cross the RPC wire, so the worker matches
        folded tenant names instead. The one divergence (an "other"
        overflow scope names no raw tenant remotely) self-heals via
        the escalation path."""
        tenants = (None if covers is None
                   else list(self._brownout_scope or ()))
        for h in self._alive():
            for rid in h.shed_queued(self.config.shed_priority,
                                     covers=covers, tenants=tenants):
                tr = self.tracked.get(rid)
                if tr is not None and not tr.done:
                    # slo_exempt: see submit() — the brown-out's own
                    # sheds must not burn the SLO that drives it
                    self._finalize(tr, list(tr.prefix), "shed",
                                   slo_exempt=True)
                    self.metrics.on_shed("brownout")

    def _update_brownout(self) -> None:
        """Brown-out has TWO triggers: fleet pressure (the PR-2
        occupancy heuristic) and SLO burn (serve/slo.py — pressure is a
        proxy; a burning TTFT/error-rate SLO is the measured thing the
        proxy stands for). Either engages it; disengage requires BOTH
        pressure under `brownout_off` and no active SLO alert — the
        pressure hysteresis band and the watchdog's trip/resolve
        asymmetry compose, so neither trigger can flap the mode.

        An SLO-only trip against a TenantSLORegistry is TENANT-SCOPED:
        only the burning tenants' low-priority work sheds (door and
        queues) — per-tenant budgets exist precisely so a hostile
        tenant's burn cannot cost the compliant tenant's requests. The
        scope tracks the burning set while engaged and ESCALATES to
        global if pressure later crosses `brownout_on` (overload is
        everyone's problem, whoever caused it)."""
        cfg = self.config
        alive = self._alive()
        slots = sum(h.max_slots for h in alive)
        work = sum(h.queue_len + h.active for h in alive)
        pressure = (work / slots) if slots else float("inf")
        self.metrics.fleet_pressure.set(min(pressure, 1e9))
        slo_burning = self.slo is not None and self.slo.active
        traced = self.tracer is not None and self.tracer.enabled
        burning_fn = getattr(self.slo, "burning_tenants", None)
        if not self.brownout and (pressure >= cfg.brownout_on
                                  or slo_burning):
            self.brownout = True
            scope = None
            if pressure < cfg.brownout_on and burning_fn is not None:
                scope = tuple(burning_fn())
            self._brownout_scope = scope
            self.metrics.brownout_active.set(1)
            if traced:
                attrs = dict(pressure=round(pressure, 3),
                             trigger=("pressure"
                                      if pressure >= cfg.brownout_on
                                      else "slo"))
                if scope is not None:
                    attrs["tenants"] = ",".join(scope)
                self.tracer.instant("brownout_on", pid=ROUTER_PID,
                                    **attrs)
            self._shed_brownout_queued(
                None if scope is None else self._brownout_covers)
        elif self.brownout and pressure <= cfg.brownout_off \
                and not slo_burning:
            self.brownout = False
            self._brownout_scope = None
            self.metrics.brownout_active.set(0)
            if traced:
                self.tracer.instant("brownout_off", pid=ROUTER_PID,
                                    pressure=round(pressure, 3))
        elif self.brownout and self._brownout_scope is not None:
            # engaged and tenant-scoped: keep the scope current
            if pressure >= cfg.brownout_on:
                # overload joined the party — escalate to global and
                # shed the backlog the scoped pass left untouched
                self._brownout_scope = None
                if traced:
                    self.tracer.instant("brownout_escalate",
                                        pid=ROUTER_PID,
                                        pressure=round(pressure, 3))
                self._shed_brownout_queued(None)
            elif burning_fn is not None:
                now_burning = tuple(burning_fn())
                newly = set(now_burning) - set(self._brownout_scope)
                self._brownout_scope = now_burning
                if newly:
                    # a tenant that STARTED burning mid-brown-out gets
                    # the same treatment the original offenders got
                    self._shed_brownout_queued(self._brownout_covers)

    # ---------------------------------------------------------- finalize
    def _finalize(self, tr: _Tracked, tokens: List[int], status: str,
                  first_token_time: Optional[float] = None,
                  slo_exempt: bool = False) -> Completion:
        now = self.clock.now()
        req = tr.req
        ttft = tpot = None
        if first_token_time is not None:
            ttft = first_token_time - req.arrival
            if len(tokens) > 1:
                tpot = (now - first_token_time) / (len(tokens) - 1)
        total = now - req.arrival
        flight = {
            "queue_s": tr.queue_s, "prefill_s": tr.prefill_s,
            "decode_s": tr.decode_s,
            # latency not spent on any replica: parked in the retry
            # heap, dead-replica gaps, pre-submit trace lateness
            "stall_s": max(
                0.0, total - tr.queue_s - tr.prefill_s - tr.decode_s
            ),
            "retries": tr.retries, "failovers": tr.failovers,
        }
        if tr.spec_drafted:
            flight["spec_drafted"] = tr.spec_drafted
            flight["spec_accepted"] = tr.spec_accepted
            flight["spec_accept_rate"] = tr.spec_accepted / tr.spec_drafted
        if tr.route is not None:
            # the routing decision behind this request's placement and
            # the prefix tokens its replicas served warm — the flight
            # record says WHY a request was fast (affinity hit) or not
            flight["route"] = tr.route
            flight["prefix_hit_tokens"] = tr.prefix_hit_tokens
        st = self.streams.get(req.rid)
        if st is not None and not st.closed:
            # flush the authoritative tail (tokens the completion holds
            # that never rode a chunk — at most the last burst), then
            # the typed end. A shed mid-stream lands HERE with status
            # "shed": the stream terminates with a reason, not silence.
            if len(tokens) > st.delivered:
                self._stream_emit(st, "tokens", start=st.delivered,
                                  tokens=tokens[st.delivered:])
                st.delivered = len(tokens)
            self._stream_emit(st, "end", status=status)
            # attribute the failover stall: time between resume markers
            # and their next delivered edge, measured at the consumer
            flight["resume_gap_s"] = st.resume_gap_s
        c = Completion(
            rid=req.rid, tokens=tokens, status=status,
            arrival=req.arrival, finish=now, ttft=ttft, tpot=tpot,
            flight=flight, trace_id=req.trace_id, tenant=req.tenant,
        )
        if self.tracer is not None:
            # tail verdict on the ROUTER's recorder (the fleet
            # timeline): keeps on bad status, any retry/failover hop,
            # or end-to-end latency past the slow threshold. The
            # outcome gates the fleet histogram exemplars below.
            c.trace_sampled = self.tracer.finish_trace(
                req.trace_id, status=status, latency_s=total,
                retries=tr.retries, failovers=tr.failovers)
        tr.done = True
        self._pending -= 1
        # drop the tracking entry so live state stays O(in-flight) and
        # rids may be reused; late sub-completions for this rid just miss
        # the lookup and are skipped. (self.completions keeps the result
        # history — the same accumulate-and-consume contract as
        # Scheduler.completions; a drain API is recorded follow-up.)
        self.tracked.pop(req.rid, None)
        self.completions.append(c)
        self.metrics.on_finalize(c)
        if self.ledger is not None:
            # cost metering (serve/fairshare.py): one fold per terminal,
            # prompt length from the request (the Completion doesn't
            # carry the prompt), phases/prefix hits off the flight
            self.ledger.on_completion(c, prompt_tokens=len(req.prompt))
        if self.telemetry is not None:
            # the exemption travels with the flight line, so the
            # offline verdict (tools/check_slo.py) reproduces the
            # online judgment
            self.telemetry.on_completion(c, slo_exempt=slo_exempt)
        if self.slo is not None and not slo_exempt:
            # brown-out's own sheds are exempt (anti-windup): counting
            # the degradation response as an SLO failure would hold the
            # alert — and therefore the brown-out — active forever
            self.slo.observe(c)
        return c

    # ------------------------------------------------------------- misc
    @property
    def idle(self) -> bool:
        return self._pending == 0

    def run_until_idle(self, max_ticks: int = 100_000) -> List[Completion]:
        for _ in range(max_ticks):
            if self.idle:
                return self.completions
            self.step()
        raise RuntimeError(f"not idle after {max_ticks} ticks")

    def warmup(self, widths: Optional[Sequence[int]] = None) -> None:
        """Compile each replica's programs outside any timed/traced
        window: one admit per bucket width in play + one decode burst.
        After this, request churn (and failover re-prefills, which land
        in the same buckets) causes zero new compiles — the chaos tests
        pin that via compile_stats(). (Worker processes warm themselves
        before signalling ready — their handle's warmup is a no-op.)"""
        for h in self.handles:
            h.warmup(widths)

    def compile_stats(self) -> Dict[int, dict]:
        return {h.id: h.compile_stats() for h in self.handles}

    def states(self) -> Dict[int, str]:
        return {h.id: h.health.state.value for h in self.handles}


def make_router(
    model,
    params,
    n_replicas: int,
    engine_config: EngineConfig,
    *,
    clock=None,
    max_queue: int = 64,
    config: RouterConfig = RouterConfig(),
    fault_plan: Optional[FaultPlan] = None,
    registry: Optional[MetricsRegistry] = None,
    batch_stats=None,
    tracer=None,
    slo=None,
    telemetry=None,
    trace_sample: float = 1.0,
    trace_keep_slow_s: Optional[float] = None,
    trace_tenant_rates: Optional[dict] = None,
    vtc=None,
    ledger=None,
) -> Router:
    """Build a fleet of identical replicas (replicated params — the
    sharded-params variant is ROADMAP follow-up) on one shared clock,
    each with its own ServeMetrics (the routing gauges) and, when a
    FaultPlan targets it, its own deterministic injector. `tracer`
    (utils/trace.py TraceRecorder) threads one recorder through the
    router, every scheduler, and every engine — pid=replica, labelled
    lanes — for `--trace-out` Chrome-trace export. `trace_sample` /
    `trace_keep_slow_s` / `trace_tenant_rates` attach the head-sampling
    + tail-keep policy to that recorder (default: record everything)."""
    if n_replicas < 1:
        raise ValueError("n_replicas must be >= 1")
    clock = clock or MonotonicClock()
    if config.fair and vtc is None:
        from ddp_practice_tpu.serve.fairshare import VirtualTokenCounter
        vtc = VirtualTokenCounter()
    if tracer is not None and (trace_sample < 1.0
                               or trace_keep_slow_s is not None
                               or trace_tenant_rates):
        tracer.set_sampler(
            TraceSampler(trace_sample, keep_slow_s=trace_keep_slow_s,
                         tenant_rates=trace_tenant_rates),
            registry=registry,
        )
    schedulers = []
    for i in range(n_replicas):
        engine = SlotEngine(
            model, params, engine_config, batch_stats=batch_stats
        )
        if tracer is not None:
            engine.set_tracer(tracer, i)
            label_replica(tracer, i, engine_config.max_slots)
        schedulers.append(Scheduler(
            engine, clock=clock, max_queue=max_queue,
            metrics=ServeMetrics(),
            fault_hook=fault_plan.injector(i) if fault_plan else None,
            tracer=tracer, replica=i, vtc=vtc,
        ))
    return Router(
        schedulers, clock=clock, config=config,
        metrics=RouterMetrics(registry), tracer=tracer,
        slo=slo, telemetry=telemetry, vtc=vtc, ledger=ledger,
    )
