"""Worker process: one serving replica as a real OS process.

`python -m ddp_practice_tpu.serve.worker --spec <json|@path>` boots a
complete single-replica serving stack — its own single-process JAX
runtime and devices, its own model/params (deterministic init from the
spec, or a checkpoint), its own Scheduler + SlotEngine/PagedEngine —
and serves two planes:

- the serve/rpc.py seam (``submit`` / ``poll`` / ``ping`` / ``shed`` /
  ``drain`` / ``shutdown``), cut at exactly Scheduler.submit and the
  completions watermark, for the router in the supervisor process;
- the PR-5 telemetry endpoints (``/metrics`` ``/healthz`` ``/flight``,
  utils/telemetry.py TelemetryServer) for the fleet-level scrape
  federator.

The worker drives its own serve loop (a scheduler tick whenever work is
queued) — the router does NOT tick remote replicas; its per-tick call
is the heartbeat+watermark ``poll``. Every RPC op is IDEMPOTENT so the
client may retry transport failures: submit dedups by rid, poll reads
from a client-held watermark, ping/shed/drain repeat safely.

Ready protocol: after the engine warms its prefill/decode programs, the
worker prints one line ``WORKER_READY {json}`` (pid + bound ports) to
stdout and flushes. The supervisor tails the worker's log file for that
line — compile time is paid BEFORE the worker joins dispatch, so a
restarted replica re-warms from scratch and rejoins only after a
passing health probe, never cold.

NOTE this is a plain OS process with single-process JAX — no
jax.distributed rendezvous, no cross-process collectives (this image's
CPU backend refuses them anyway, tests/mp_worker.py rc-77 probe).
Workers share nothing but the RPC wire; params are replicated by
construction (same spec, same PRNGKey init — or the same checkpoint),
which is exactly the replicated-fleet contract the in-process router
had. Sharded-params replicas (one logical replica spanning a mesh)
remain a ROADMAP item.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time
from typing import Optional


@dataclasses.dataclass
class WorkerSpec:
    """Everything a worker needs to become a replica, JSON-serializable
    (passed on argv — the spec IS the replica's identity, so a
    supervisor restart rebuilds a bit-identical one)."""

    # model architecture kwargs (deterministic PRNGKey(0) init — every
    # worker with the same spec holds byte-identical params)
    model: dict = dataclasses.field(default_factory=dict)
    # EngineConfig kwargs, plus "paged": true to build a PagedEngine
    engine: dict = dataclasses.field(default_factory=dict)
    replica: int = 0            # id in fleet telemetry / lane labels
    max_queue: int = 64
    rpc_port: int = 0           # 0 = ephemeral, reported in READY
    telemetry_port: int = 0
    warmup: bool = True
    platform: str = "cpu"       # jax platform pin ("" = leave alone)
    # fleet tracing: record this replica's prefill/decode_burst/queued/
    # request spans (utils/trace.py) and stream them back to the router
    # as batched `trace` push frames, where the TraceCollector merges
    # them into the fleet timeline. Off = zero recording (the PR-4
    # disabled-tracer contract).
    trace: bool = False
    trace_buffer: int = 4096    # pending-events bound (drops counted)
    # head-sampling rate for the trace plane (1.0 = record everything,
    # the pre-sampling behavior). The ROUTER decides per trace_id and
    # propagates the decision on the wire; this local policy covers
    # direct submits and lets the worker agree deterministically when
    # no upstream decision rode along (same crc32 hash, same answer).
    trace_sample: float = 1.0
    # tail keep-rule: head-unsampled requests slower than this are
    # promoted to kept at completion (None = no latency rule)
    trace_keep_slow_s: Optional[float] = None
    # per-tenant head-rate overrides (tenant id -> rate). Same Dapper
    # coherence as trace_sample: the router decides per trace_id and
    # the decision rides the wire, but a direct submit consults the
    # same table and agrees.
    trace_tenant_rates: Optional[dict] = None
    # token streaming: the scheduler emits per-burst TokenChunks and
    # the worker ships them inside its `pub` push frames (atomically
    # with the inflight salvage point — a dropped frame loses both
    # together, so the router's resume cursor never outruns delivery).
    # False = end-of-request delivery (the overhead bench's control).
    stream: bool = True
    # speculative decoding (serve/spec.py): first-class spec fields so
    # fleet launchers can flip the feature without knowing EngineConfig
    # internals; folded into the engine kwargs at build time. Only
    # meaningful for paged workers (the SlotEngine refuses it).
    spec_decode: bool = False
    spec_k: int = 4
    # weighted-fair scheduling (serve/fairshare.py): the worker builds
    # its own VirtualTokenCounter + TenantLedger, the scheduler picks
    # the least-served tenant's queue head, and /tenants serves the
    # per-tenant cost rollup. Off = byte-identical FIFO (no VTC
    # exists) — the same contract as RouterConfig.fair in-process.
    fair: bool = False

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, text: str) -> "WorkerSpec":
        return cls(**json.loads(text))


READY_PREFIX = "WORKER_READY "


class _TelemetryFanout:
    """Scheduler takes ONE telemetry object; a fair worker needs two
    sinks per completion (FlightStats window + TenantLedger billing).
    Tiny fan-out instead of widening the scheduler seam."""

    def __init__(self, *sinks) -> None:
        self.sinks = sinks

    def on_completion(self, completion, **kw) -> None:
        for s in self.sinks:
            s.on_completion(completion, **kw)


class _TraceBuffer:
    """Bounded holding pen between the worker's TraceRecorder sink and
    the push stream: spans are recorded mid-burst (under the big lock),
    drained into one batched ``trace`` frame per publish. Bounded the
    same way the TelemetryExporter queue is — a stalled stream drops
    the OLDEST pending events and counts them (`dropped` rides every
    frame, cumulative, so the router-side collector books the loss),
    it never grows without bound and never stalls the serve loop."""

    def __init__(self, maxlen: int = 4096) -> None:
        self._lock = threading.Lock()
        self._buf: list = []
        self._maxlen = maxlen
        self.dropped = 0

    def put(self, rec: dict) -> None:
        with self._lock:
            if len(self._buf) >= self._maxlen:
                del self._buf[0]
                self.dropped += 1
            self._buf.append(rec)

    def drain(self) -> list:
        with self._lock:
            out, self._buf = self._buf, []
        return out

    def note_drops(self, n: int) -> None:
        with self._lock:
            self.dropped += n

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


def build_model(model_kw: dict):
    """The bench's tiny-LM recipe (serve/bench.py), spec-driven: same
    kwargs + PRNGKey(0) init in every process -> replicated params."""
    import jax
    import jax.numpy as jnp

    from ddp_practice_tpu.models import create_model

    kw = dict(model_kw)
    name = kw.pop("name", "lm_tiny")
    model = create_model(name, **kw)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


class WorkerServer:
    """The replica's in-process wiring: scheduler + engine behind the
    RPC handlers, telemetry on the side, one lock serializing every
    state mutation against the serve loop."""

    def __init__(self, spec: WorkerSpec) -> None:
        from ddp_practice_tpu.serve.engine import (
            EngineConfig,
            PagedEngine,
            SlotEngine,
        )
        from ddp_practice_tpu.serve.metrics import ServeMetrics
        from ddp_practice_tpu.serve.rpc import RpcServer
        from ddp_practice_tpu.serve.scheduler import Scheduler
        from ddp_practice_tpu.utils.metrics import MetricsRegistry
        from ddp_practice_tpu.utils.telemetry import (
            FlightStats,
            TelemetryServer,
        )

        self.spec = spec
        model, params = build_model(spec.model)
        eng_kw = dict(spec.engine)
        paged = bool(eng_kw.pop("paged", False))
        if "prompt_buckets" in eng_kw:
            eng_kw["prompt_buckets"] = tuple(eng_kw["prompt_buckets"])
        if spec.spec_decode:
            eng_kw.setdefault("spec_decode", True)
            eng_kw.setdefault("spec_k", spec.spec_k)
        cfg = EngineConfig(**eng_kw)
        engine_cls = PagedEngine if paged else SlotEngine
        self.engine = engine_cls(model, params, cfg)
        # prefix-digest publisher (serve/affinity.py): fingerprints the
        # warm radix tree into every heartbeat so the router can route
        # by expected prefix hit. None without a prefix cache — the
        # kv summary simply carries no digest and the router falls back
        # to least-loaded.
        radix = getattr(self.engine, "radix", None)
        if radix is not None:
            from ddp_practice_tpu.serve.affinity import DigestPublisher

            self._digest = DigestPublisher(radix)
        else:
            self._digest = None
        self.registry = MetricsRegistry()
        self.flight = FlightStats()
        self.ledger = None
        vtc = None
        if spec.fair:
            from ddp_practice_tpu.serve.fairshare import (
                TenantLedger,
                VirtualTokenCounter,
            )

            vtc = VirtualTokenCounter()
            self.ledger = TenantLedger(registry=self.registry, vtc=vtc)
        self.scheduler = Scheduler(
            self.engine, max_queue=spec.max_queue,
            metrics=ServeMetrics(self.registry),
            telemetry=(self.flight if self.ledger is None
                       else _TelemetryFanout(self.flight, self.ledger)),
            replica=spec.replica,
            stream=spec.stream, vtc=vtc,
        )
        # two-lock discipline so the RPC plane NEVER waits out a decode
        # burst: `_lock` (the big one) serializes scheduler/engine
        # mutation and is held across a whole step(); `_io_lock` guards
        # only the intake list and the published snapshot, held for
        # microseconds. submit appends to intake, poll reads the last
        # published snapshot — both return in ~an RTT while the burst
        # runs. (Measured: handler-behind-the-burst cost the RPC seam
        # most of its latency overhead at 8 rps.)
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._intake: list = []
        self._published: dict = {
            "completions_len": 0, "chunks_len": 0,
            "inflight": [], "stats": None,
        }
        self._pub_version = 0
        # push subscribers: [{"q": Queue, "watermark": int}] — _publish
        # enqueues one frame per snapshot, the RpcServer push loop owns
        # the socket. Queues are bounded; a slow/stuck subscriber drops
        # frames (its poll path reconciles) rather than stalling steps.
        self._subscribers: list = []
        self._last_push = 0.0
        self._last_pushed_upto = 0
        self._last_pushed_cupto = 0
        self._stop = threading.Event()
        self._wake = threading.Event()   # submit -> serve loop, no spin
        self._draining = False
        self._drain_exit = False         # SIGTERM: exit once drained
        self._seen_rids: dict = {}   # rid -> accepted (submit dedup)
        self._t0 = time.monotonic()
        # fleet tracing (spec.trace): this replica's own span recorder,
        # draining through a bounded buffer into batched `trace` push
        # frames (see _publish). The ring buffer is small — the ROUTER
        # holds the fleet timeline; this one only backs the stream.
        self._tracer = None
        self._trace_buf: Optional[_TraceBuffer] = None
        self._trace_seq = 0
        self._last_trace_dropped = 0
        if spec.warmup:
            self._warm()
        if spec.trace:
            # attached only AFTER warmup, so compile-time spans never
            # enter the stream (the bench/router warmup-clear contract)
            from ddp_practice_tpu.utils.trace import (
                TraceRecorder,
                TraceSampler,
                label_replica,
            )

            self._trace_buf = _TraceBuffer(spec.trace_buffer)
            self._tracer = TraceRecorder(
                max_events=spec.trace_buffer, sink=self._trace_buf.put,
            )
            if (spec.trace_sample < 1.0
                    or spec.trace_keep_slow_s is not None
                    or spec.trace_tenant_rates):
                # upstream suppression is THE point: unsampled requests
                # never enter this buffer or the push stream — they wait
                # in the recorder's per-request staging for a tail
                # verdict, and only kept spans ride the wire
                self._tracer.set_sampler(
                    TraceSampler(spec.trace_sample,
                                 keep_slow_s=spec.trace_keep_slow_s,
                                 tenant_rates=spec.trace_tenant_rates),
                    registry=self.registry,
                )
            label_replica(self._tracer, spec.replica,
                          self.engine.config.max_slots)
            self.scheduler.tracer = self._tracer
            self.engine.set_tracer(self._tracer, spec.replica)
        with self._lock:
            self._publish()   # ping/poll answer before the first step
        # planes come up only after warmup: a worker is dispatchable
        # the moment its ports are visible, so visible == warm
        self.telemetry = TelemetryServer(
            registry=self.registry,
            health_fn=lambda: {spec.replica: "healthy"},
            flight_fn=self.flight.report,
            tenants_fn=(self.ledger.report
                        if self.ledger is not None else None),
            port=spec.telemetry_port,
        )
        self.rpc = RpcServer({
            "ping": self._op_ping,
            "submit": self._op_submit,
            "poll": self._op_poll,
            "subscribe": self._op_subscribe,
            "reset": self._op_reset,
            "shed": self._op_shed,
            "drain": self._op_drain,
            "trace": self._op_trace,
            "shutdown": self._op_shutdown,
        }, port=spec.rpc_port)

    def _warm(self) -> None:
        from ddp_practice_tpu.serve.engine import warm_engine

        warm_engine(self.engine)

    # ------------------------------------------------------------- ops
    def _kv_summary(self) -> dict:
        """KV/radix-cache occupancy riding every heartbeat frame: blocks
        in use / shared, prefix-cache hit rate, evictable count — plus
        the prefix digest (serve/affinity.py) cache-aware routing scores
        against. Zeros (and no digest) for the slot engine. Federated
        into per-worker gauges by the fleet view; the router's affinity
        index feeds straight off this payload."""
        from ddp_practice_tpu.serve.affinity import kv_summary

        return kv_summary(self.engine, self._digest)

    def _stats(self) -> dict:
        return {
            "kv": self._kv_summary(),
            "replica": self.spec.replica,
            "pid": os.getpid(),
            "t": time.monotonic(),
            "uptime_s": time.monotonic() - self._t0,
            "queue": len(self.scheduler.queue),
            "active": self.engine.num_active,
            "max_slots": self.engine.config.max_slots,
            "max_queue": self.scheduler.max_queue,
            "completions": len(self.scheduler.completions),
            "draining": self._draining,
            # post-warmup these are CONSTANT under churn (the
            # compile_guard invariant) — refreshed per publish anyway,
            # it is two dict-len reads
            "compile_stats": self.engine.compile_stats(),
        }

    def _op_ping(self, req: dict) -> dict:
        with self._io_lock:
            stats = self._published["stats"]
        if stats is None:
            with self._lock:
                stats = self._stats()
        # "t" is THIS clock read during handling — the remote timestamp
        # of the NTP-style offset sample the caller may be taking
        # (utils/trace.py ClockOffsetEstimator); the snapshot stats'
        # own "t" is stale by up to a publish interval
        return {"stats": stats, "t": time.monotonic()}

    def _op_submit(self, req: dict) -> dict:
        from ddp_practice_tpu.serve.scheduler import Request

        r = req["request"]
        rid = r["rid"]
        with self._io_lock:
            if rid in self._seen_rids:
                # transport-retry replay: answer what we answered
                return {"accepted": self._seen_rids[rid], "dedup": True}
            if self._draining:
                self._seen_rids[rid] = False
                return {"accepted": False, "draining": True}
            # intake only — the serve loop drains into the scheduler at
            # the top of its next iteration (exactly when an in-process
            # scheduler would admit a just-queued request). A shed or
            # reject still lands as a completion in a later poll.
            self._intake.append(Request(
                rid=rid,
                prompt=list(r["prompt"]),
                max_new_tokens=r.get("max_new_tokens", 32),
                deadline=r.get("deadline"),
                seed=r.get("seed", 0),
                arrival=r.get("arrival"),
                priority=r.get("priority", 0),
                trace_id=r.get("trace_id"),
                # the router's head decision rides the wire (Dapper
                # coherence); absent → the scheduler re-derives it from
                # the same deterministic hash and agrees anyway
                sampled=r.get("sampled"),
                tenant=r.get("tenant"),
                temperature=r.get("temperature"),
                top_k=r.get("top_k"),
                top_p=r.get("top_p"),
            ))
            self._seen_rids[rid] = True
            # the dedup window only needs to outlive a transport retry
            # (seconds) — cap the map so a long-lived worker doesn't
            # retain every rid it ever served (dicts iterate in
            # insertion order: the popped entries are the oldest)
            while len(self._seen_rids) > 8192:
                del self._seen_rids[next(iter(self._seen_rids))]
        self._wake.set()
        return {"accepted": True}

    @staticmethod
    def _completion_dict(c) -> dict:
        return {
            "rid": c.rid, "tokens": list(c.tokens), "status": c.status,
            "arrival": c.arrival, "finish": c.finish,
            "ttft": c.ttft, "tpot": c.tpot, "flight": c.flight,
            "trace_id": c.trace_id,
            # the worker-side keep verdict, so the router's exemplar
            # gating sees whether this attempt's spans are in the stream
            "sampled": getattr(c, "trace_sampled", True),
            "tenant": getattr(c, "tenant", None),
        }

    def _publish(self) -> None:
        """Snapshot scheduler state for the RPC plane — called by the
        serve loop under the BIG lock after every mutation, read by
        handlers under the io lock only. Completion dicts are built
        lazily at read (the list is append-only; a published length
        bounds what a poll may see)."""
        inflight = [
            {"rid": r.rid, "tokens": list(toks), "ftt": ftt,
             "phases": phases}
            for r, toks, ftt, phases in self.scheduler.inflight_snapshot()
        ]
        stats = self._stats()
        comps = self.scheduler.completions
        upto = len(comps)
        chunks = self.scheduler.chunks   # append-only, like completions
        cupto = len(chunks)
        with self._io_lock:
            self._pub_version += 1
            version = self._pub_version
            self._published = {
                "completions_len": upto,
                "chunks_len": cupto,
                "inflight": inflight,
                "stats": stats,
            }
            subs = list(self._subscribers)
        # push to subscribers only when a COMPLETION or a token chunk
        # moved (the latency-critical events — streaming TTFT/ITL are
        # measured off these frames) or the 50 ms freshness beat is
        # due: pushing every decode step taxed the same single core the
        # decode runs on, for frames that carried nothing new. With
        # streaming on, a burst that decoded tokens always moved cupto,
        # so the chunk plane rides per-burst frames; the overhead bench
        # bills exactly this extra push traffic against the ≤1.05x bar.
        if subs and upto == self._last_pushed_upto \
                and cupto == self._last_pushed_cupto \
                and time.monotonic() - self._last_push < 0.05:
            return
        # (outside the io lock — the queues are thread-safe; completion
        # dicts are built per subscriber from its own watermark)
        for sub in subs:
            wm = sub["watermark"]
            cwm = sub["cwm"]
            # chunks ride IN the pub frame (not a separate frame kind):
            # a dropped frame loses the chunk slice and the inflight
            # salvage point TOGETHER, so the router's resume cursor can
            # never run ahead of the chunks it suppresses against
            frame = {
                "kind": "pub", "version": version,
                "from": wm, "watermark": upto,
                "completions": [
                    self._completion_dict(c) for c in comps[wm:upto]
                ],
                "chunks": [c.to_dict() for c in chunks[cwm:cupto]],
                "chunks_from": cwm, "chunks_watermark": cupto,
                "inflight": inflight, "stats": stats,
            }
            try:
                sub["q"].put_nowait(frame)
                sub["watermark"] = upto
                sub["cwm"] = cupto
            except Exception:
                pass  # full queue: this frame drops, poll reconciles
        # trace events drain ONLY toward live subscribers: with none,
        # they stay buffered (the bounded buffer ages them out, counted)
        # instead of being drained into a frame nobody receives —
        # loss is counted, never silent
        tf = self._trace_frame() if subs else None
        if tf is not None:
            for sub in subs:
                try:
                    sub["q"].put_nowait(tf)
                except Exception:
                    # a full push queue loses these events for good —
                    # book them so the next frame's cumulative count
                    # tells the collector the timeline has a hole
                    self._trace_buf.note_drops(len(tf["events"]))
        self._last_push = time.monotonic()
        self._last_pushed_upto = upto
        self._last_pushed_cupto = cupto

    def _trace_frame(self) -> Optional[dict]:
        """Drain pending trace events into one batched push frame
        (None when nothing new happened). `seq` dedups transport
        replays at the collector; `dropped` is cumulative."""
        if self._trace_buf is None:
            return None
        events = self._trace_buf.drain()
        dropped = self._trace_buf.dropped
        if not events and dropped == self._last_trace_dropped:
            return None
        self._trace_seq += 1
        self._last_trace_dropped = dropped
        return {"kind": "trace", "seq": self._trace_seq,
                "replica": self.spec.replica,
                "events": events, "dropped": dropped}

    def _op_trace(self, req: dict) -> dict:
        """Toggle span recording at runtime (idempotent). The overhead
        bench flips the whole trace plane off/on per rep against the
        same warm fleet — `enabled=false` also clears anything pending,
        so a later re-enable starts a clean stream. An optional
        ``sample`` adjusts the head rate in place (the sampling bench
        compares 1% / full / off against ONE warm fleet; the adaptive
        controller steers it live), and an optional ``tenant_rates``
        dict replaces the per-tenant override table the same way."""
        enabled = bool(req.get("enabled", True))
        sample = req.get("sample")
        tenant_rates = req.get("tenant_rates")
        if self._tracer is None:
            return {"supported": False, "enabled": False}
        with self._lock:
            if sample is not None or tenant_rates is not None:
                if self._tracer.sampler is None:
                    from ddp_practice_tpu.utils.trace import TraceSampler

                    self._tracer.set_sampler(
                        TraceSampler(
                            float(sample) if sample is not None else 1.0,
                            keep_slow_s=self.spec.trace_keep_slow_s,
                            tenant_rates=tenant_rates),
                        registry=self.registry,
                    )
                else:
                    if sample is not None:
                        self._tracer.sampler.rate = float(sample)
                    if tenant_rates is not None:
                        self._tracer.sampler.tenant_rates = {
                            str(k): float(v)
                            for k, v in tenant_rates.items()
                        } or None
            if enabled:
                self._tracer.enable()
            else:
                self._tracer.disable()
                self._tracer.clear()
                self._trace_buf.clear()
        sampler = self._tracer.sampler
        return {"supported": True, "enabled": enabled,
                "sample": None if sampler is None else sampler.rate,
                "tenant_rates": (None if sampler is None
                                 else sampler.tenant_rates)}

    def _op_poll(self, req: dict) -> dict:
        """The heartbeat + completions-watermark read. `watermark` is
        CLIENT-held (an index into this process's completions list —
        a restarted worker starts at 0 and the client resets with it).
        `inflight` is the live salvage point: rid / tokens-so-far /
        first-token-time for everything queued or decoding, so a later
        SIGKILL costs the router at most one poll interval of tokens —
        and greedy re-decode reproduces even those. Served from the
        post-step published snapshot: a poll never waits out a burst."""
        watermark = int(req.get("watermark", 0))
        cwm = int(req.get("chunks_watermark", 0))
        seen_version = req.get("version")
        confirm = req.get("confirm")
        confirmed: Optional[dict] = None
        with self._io_lock:
            version = self._pub_version
            pub = self._published
            upto = pub["completions_len"]
            cupto = pub["chunks_len"]
            inflight = pub["inflight"]
            stats = pub["stats"]
            if confirm:
                # fire-and-forget reconcile: for each rid the client
                # cast a one-way submit for, answer what _op_submit
                # recorded — True accepted, False refused (draining),
                # absent = the frame never landed (client resubmits;
                # submit is idempotent by rid). Served on the SAME
                # connection the casts rode, so TCP ordering makes
                # "absent" mean lost, not merely not-yet-processed.
                confirmed = {
                    str(rid): self._seen_rids[rid]
                    for rid in confirm if rid in self._seen_rids
                }
        if seen_version == version and watermark >= upto \
                and cwm >= cupto:
            # nothing moved since the client's last poll: answer with a
            # frame small enough that a high-rate heartbeat costs the
            # decode loop (same single core!) close to nothing. "t" =
            # this clock read (clock-offset sampling, see _op_ping).
            out = {"version": version, "unchanged": True,
                   "t": time.monotonic()}
            if confirmed is not None:
                out["confirmed"] = confirmed
            return out
        comps = self.scheduler.completions  # append-only list
        new = [self._completion_dict(c) for c in comps[watermark:upto]]
        chunks = self.scheduler.chunks      # append-only too
        new_chunks = [c.to_dict() for c in chunks[cwm:cupto]]
        if stats is None:
            with self._lock:
                stats = self._stats()
        out = {"version": version,
               "completions": new,
               "watermark": upto,
               "chunks": new_chunks,
               "chunks_from": cwm,
               "chunks_watermark": cupto,
               "inflight": inflight,
               "stats": stats,
               "t": time.monotonic()}
        if confirmed is not None:
            out["confirmed"] = confirmed
        return out

    def _drain_intake_locked(self) -> int:
        """Move intake into the scheduler (big lock held by caller)."""
        with self._io_lock:
            intake, self._intake = self._intake, []
        for r in intake:
            self.scheduler.submit(r)
        return len(intake)

    def _op_subscribe(self, req: dict) -> dict:
        """Switch this connection into a push stream (rpc.py push
        mode): every published snapshot lands as a frame, no polling.
        `watermark` is where the client's completion stream currently
        stands (a resubscribe after a stream hiccup must not replay).
        The push loop unregisters the subscriber when the stream dies —
        reconnect churn must not leave _publish building frames for a
        graveyard of dead queues."""
        import queue

        q: "queue.Queue" = queue.Queue(maxsize=256)
        sub = {"q": q, "watermark": int(req.get("watermark", 0)),
               "cwm": int(req.get("chunks_watermark", 0))}
        with self._io_lock:
            self._subscribers.append(sub)

        def closed():
            with self._io_lock:
                try:
                    self._subscribers.remove(sub)
                except ValueError:
                    pass

        return {"_stream_queue": q, "_stream_closed": closed}

    def _op_reset(self, req: dict) -> dict:
        """The remote mirror of the in-process ReplicaHandle.restart():
        a handle rejoining an incarnation it had written off (a
        transport-blip 'death' — the process never died) must find a
        CLEAN replica: stale queue/running work dropped (its requests
        were already re-dispatched on survivors; finishing them here
        would double-spend the engine and replay rid history), slots
        released, dedup history forgotten. Returns the completions
        watermark so the client resyncs instead of replaying the whole
        history from 0."""
        with self._lock:
            self._drain_intake_locked()
            slots = list(self.scheduler.running.keys())
            self.scheduler.evacuate()   # clears queue/running/_resume
            for s in slots:
                self.engine.release(s)
            with self._io_lock:
                self._seen_rids.clear()
            self._publish()
            # both watermarks so the rejoining client resyncs its chunk
            # cursor too — the evacuated attempts' chunks stay in the
            # list (append-only) but none of them will ever see a final
            # marker; skipping ahead avoids replaying them
            return {"completions": len(self.scheduler.completions),
                    "chunks": len(self.scheduler.chunks)}

    def _op_shed(self, req: dict) -> dict:
        min_priority = int(req["min_priority"])
        # tenant-scoped brown-out (serve/router.py): a name list rides
        # the wire in place of the router's exact covers-predicate;
        # None/absent = global shed
        tenants = req.get("tenants")
        scope = None if tenants is None else {
            (t if t else "default") for t in tenants
        }
        with self._lock:
            # intake items are queued-but-not-drained: shed sees them too
            self._drain_intake_locked()
            shed = self.scheduler.shed_queued(
                lambda r: r.priority >= min_priority
                and (scope is None
                     or (r.tenant if r.tenant is not None
                         else "default") in scope)
            )
            self._publish()
            return {"rids": [r.rid for r in shed]}

    def _op_drain(self, req: dict) -> dict:
        with self._io_lock:
            self._draining = True
        with self._lock:
            return {"queue": len(self.scheduler.queue),
                    "active": self.engine.num_active}

    def _op_shutdown(self, req: dict) -> dict:
        self._stop.set()
        return {"bye": True}

    def begin_drain(self) -> None:
        """The SIGTERM path: refuse new submits (typed ``draining``
        refusal — the router re-dispatches those on survivors), finish
        every in-flight request to its natural end (consumers observe
        an uninterrupted stream, NO resume marker — the graceful column
        of the failure matrix), publish the final frames, exit 0.
        Signal-handler safe: only sets flags."""
        with self._io_lock:
            self._draining = True
        self._drain_exit = True
        self._wake.set()

    # ------------------------------------------------------- the loop
    def serve_forever(self) -> None:
        """Self-driven serve loop: tick whenever work exists; otherwise
        nap. RPC handlers mutate scheduler state under the same lock a
        tick holds, so a submit lands between (not inside) bursts."""
        while not self._stop.is_set():
            with self._lock:
                moved = self._drain_intake_locked()
                idle = self.scheduler.idle
                if not idle:
                    self.scheduler.step()
                if moved or not idle:
                    self._publish()
            if idle and not moved:
                if self._drain_exit:
                    with self._io_lock:
                        pending = bool(self._intake)
                    if not pending:
                        # drained: in-flight streams ran to their
                        # natural end and were published. Give the push
                        # loop a beat to flush the final frames, then
                        # exit 0 — the supervisor reaps a clean drain,
                        # not a crash.
                        time.sleep(0.25)
                        self._stop.set()
                        break
                # a truly idle replica SLEEPS (an 0.5 ms spin here
                # measurably taxed every OTHER process on a small box);
                # a submit sets the event, so admission latency stays
                # ~one RPC, not one timeout. While sleeping, keep the
                # push subscribers' heartbeat warm.
                if time.monotonic() - self._last_push > 0.1:
                    with self._io_lock:
                        subs = list(self._subscribers)
                    for sub in subs:
                        try:
                            sub["q"].put_nowait(
                                {"kind": "hb", "t": time.monotonic()}
                            )
                        except Exception:
                            pass
                    self._last_push = time.monotonic()
                self._wake.wait(0.05)
                self._wake.clear()
        # give the shutdown reply a beat to flush before teardown
        time.sleep(0.1)

    def close(self) -> None:
        self._stop.set()
        self.rpc.close()
        self.telemetry.close()

    def ready_line(self) -> str:
        return READY_PREFIX + json.dumps({
            "pid": os.getpid(),
            "replica": self.spec.replica,
            "rpc_port": self.rpc.port,
            "telemetry_port": self.telemetry.port,
        })


def main(argv=None) -> int:
    p = argparse.ArgumentParser("ddp_practice_tpu.serve.worker")
    p.add_argument("--spec", required=True,
                   help="WorkerSpec JSON, or @path to a JSON file")
    args = p.parse_args(argv)
    text = args.spec
    if text.startswith("@"):
        with open(text[1:]) as f:
            text = f.read()
    spec = WorkerSpec.from_json(text)
    if spec.platform:
        # pin the platform BEFORE jax initializes a backend (the heavy
        # imports all hide inside WorkerServer)
        os.environ.setdefault("JAX_PLATFORMS", spec.platform)
    server = WorkerServer(spec)
    # graceful SIGTERM: finish in-flight work, refuse new submits, exit
    # 0 once idle (handler only sets flags — never runs mid-burst)
    import signal

    signal.signal(signal.SIGTERM, lambda *_: server.begin_drain())
    print(server.ready_line(), flush=True)
    try:
        server.serve_forever()
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
