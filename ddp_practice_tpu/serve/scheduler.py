"""Serving scheduler: FIFO admission, deadlines, shedding, slot churn.

Policy layer over the SlotEngine mechanism. One `step()` is one
scheduler tick:

1. expire queued requests whose deadline already passed (they would
   burn prefill FLOPs to produce tokens nobody is waiting for);
2. admit from the FIFO queue into free slots — prefill interleaves with
   the running decode batch at slot granularity, the continuous-batching
   move (a request admitted at tick t decodes its first token at tick
   t together with every running request's next token);
3. run one batched decode step, hand each active request its token, and
   release slots on EOS / length cap / deadline.

Admission control is two-tier: `submit()` SHEDS when the bounded queue
is full (backpressure at the door — the overload answer for "heavy
traffic from millions of users" is a fast no, not an unbounded queue),
and the admit loop asks the ENGINE's `admit_gate` for everything
memory-shaped: "never" (prompt outgrows every bucket — after any
prefix-cache match — or the request can never fit even an empty pool)
is a fast reject, "later" waits for memory. Memory policy lives behind
that gate — the slot engine answers from its shared-cursor headroom
and frees positions only via `make_room` (drain + epoch rewind,
kv_slots.py); the paged engine answers from free + prefix-cache-
evictable blocks (kv_pages.py), which release per-request, age out of
the radix cache (its make_room), or are taken back by BLOCK-AWARE
PREEMPTION. This file carries no epoch logic at all — but it does own
the preemption POLICY: when the engine evicts a slot (mid-decode
growth exhaustion, `take_preempted`) or the admit loop evicts one for
a blocked older request (`_preempt_victim_for` — only ever a
strictly-younger arrival, so readmission cascades terminate), the
victim's request re-queues at the front and re-prefills
prompt+tokens-so-far; `_resume` folds the pre-eviction tokens back
into the one completion the client sees.

Time is injected: the real server uses the monotonic clock, tests use
`FakeClock` (a fixed virtual step per engine tick), so a 20-request
trace with deadlines replays bit-for-bit deterministically on CPU.

Observability rides the same injected clock: an optional TraceRecorder
(utils/trace.py) gets per-request "queued"/"request" lifecycle spans and
shed/timeout/error instants from here (the engines record their own
prefill/decode-burst lane spans), and every Completion carries a flight
record — queue_s / prefill_s / decode_s / stall_s — computed from the
admission timestamps whether or not a tracer is attached. `tracer=None`
(the default) costs one `is not None` test per lifecycle edge.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence

from ddp_practice_tpu.serve.engine import SlotEngine
from ddp_practice_tpu.utils.trace import ENGINE_LANE


class MonotonicClock:
    """Wall time; `tick()` is a no-op (real time advances by itself)."""

    def now(self) -> float:
        return time.monotonic()

    def tick(self) -> None:
        pass


class FakeClock:
    """Deterministic virtual time: one engine step = `step_s` seconds."""

    def __init__(self, start: float = 0.0, step_s: float = 0.01) -> None:
        self._now = start
        self.step_s = step_s

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        self._now += dt

    def tick(self) -> None:
        self._now += self.step_s


@dataclasses.dataclass
class Request:
    rid: int
    prompt: Sequence[int]
    max_new_tokens: int = 32
    # absolute deadline (clock domain); None = no deadline. Expired in
    # queue -> timeout without prefill; expired while running -> early
    # release with the tokens produced so far.
    deadline: Optional[float] = None
    seed: int = 0
    # stamped by submit() when None; pre-set it (clock domain) when the
    # TRUE arrival predates the submit call — e.g. the bench replays a
    # trace and may poll arrivals a tick late; latency must not quietly
    # exclude that wait
    arrival: Optional[float] = None
    # priority class: 0 = interactive (never brown-out shed), larger =
    # more sheddable. The single-replica scheduler serves FIFO regardless
    # — priority is the ROUTER's degradation signal (serve/router.py
    # sheds priority >= its threshold while browned out).
    priority: int = 0
    # stable id linking every span this request produces — across retry
    # and failover re-admissions (the router stamps it once and passes
    # it through to sub-requests, so a crash-migrated request renders as
    # ONE timeline). Stamped "r{rid}" by submit() when None.
    trace_id: Optional[str] = None
    # the head-sampling decision for trace_id (Dapper coherence: decided
    # ONCE at router/scheduler admission, propagated through the RPC
    # seam so a worker never re-rolls it). None = undecided — stamped by
    # submit() from the tracer's sampler; stays None when sampling is
    # off (everything records, the pre-sampling behavior).
    sampled: Optional[bool] = None
    # tenant id — rides like trace_id across every seam (router, RPC,
    # worker, completion, flight record). It is the per-tenant sampling
    # key (TraceSampler.tenant_rates overrides) and the tenant= metric
    # label (behind the labelled() cardinality guard). None = untenanted
    # (single-tenant deployments pay nothing).
    tenant: Optional[str] = None
    # when submit() actually ran (clock domain; stamped by submit) —
    # flight records measure in-queue wait from here. `arrival` may
    # predate it (trace replays poll late; failover re-admissions keep
    # the ORIGINAL arrival): that earlier wait lands in stall_s, not
    # queue_s, so per-replica queue time stays honest under retries.
    submitted: Optional[float] = None
    # per-request sampling overrides (None = the engine config's
    # value). Carried across every seam like trace_id/tenant — requeue,
    # failover, RPC — and handed to the engine at admit; engines
    # without EngineConfig.per_slot_sampling REJECT overrides rather
    # than silently sampling at the wrong params.
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None


@dataclasses.dataclass
class TokenChunk:
    """One decode burst's tokens for one request — the streaming unit.

    Chunks are the scheduler's append-only side channel next to
    `completions`: consumers read them through a watermark (the same
    consume-once contract), the worker ships them inside its `pub`
    push frames (atomically with the inflight salvage point, so a
    dropped frame loses both together and the router's resume cursor
    can never run ahead of the chunks it suppresses against), and the
    router splices them into per-request TokenStreams.

    `seq` is contiguous per rid WITHIN this scheduler (attempt-local
    ordering, transport dedup); `start` is the rid-global offset of
    `tokens[0]` counting any in-scheduler preemption prefix — the
    router adds its dispatch base on top, so a chunk's tokens have an
    absolute position in the client's output and re-decoded salvage
    after failover dedups by offset, not by guesswork. Exactly one
    chunk per completion carries `final=True` + the terminal status —
    the stream's end marker."""

    rid: int
    trace_id: Optional[str]
    seq: int
    start: int
    tokens: List[int]
    t: float
    final: bool = False
    status: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "rid": self.rid, "trace_id": self.trace_id,
            "seq": self.seq, "start": self.start,
            "tokens": list(self.tokens), "t": self.t,
            "final": self.final, "status": self.status,
        }


@dataclasses.dataclass
class Completion:
    rid: int
    tokens: List[int]
    # "eos" | "length" | "timeout" | "shed" | "rejected" | "error"
    # ("error" = non-finite logits or an injected/transient engine
    # failure: the tokens already produced are VALID — they were sampled
    # from finite logits — so a router can re-admit prompt+tokens)
    status: str
    arrival: float
    finish: float
    ttft: Optional[float] = None   # arrival -> first generated token
    tpot: Optional[float] = None   # mean inter-token latency after the first
    # flight record: where this request's latency went —
    # {queue_s, prefill_s, decode_s, stall_s, retries, failovers}.
    # The scheduler fills the phase keys (retries/failovers stay 0);
    # the router re-derives them summed across attempts (router.py).
    flight: Optional[dict] = None
    # the request's trace_id, carried onto the completion so metric
    # exemplars (utils/metrics.py) and telemetry flight lines can point
    # BACK into the trace timeline — a p99 bucket names the offender
    trace_id: Optional[str] = None
    # whether trace_id actually made it into the timeline (head-sampled
    # or tail-kept). False = suppressed by sampling: exemplars must NOT
    # cite it — an exemplar pointing at a suppressed trace is a dead
    # link. True whenever sampling is off.
    trace_sampled: bool = True
    # the request's tenant, carried through so per-tenant metrics and
    # telemetry flight lines can attribute the completion
    tenant: Optional[str] = None


def _attempt_phases(req: Request, now: float,
                    admitted: Optional[tuple]) -> dict:
    """One attempt's flight-record phases up to the `now` edge.

    The single source of the phase arithmetic — `_finish` (completed
    attempts) and `evacuate` (crash-harvested attempts) must agree, or
    the router's merged stall_s residual silently skews. queue_s runs
    from submit (see Request.submitted); `admitted` is the
    (admit_t0, admit_t1) window, None while still queued.
    """
    sub = req.submitted if req.submitted is not None else req.arrival
    if admitted is None:
        return {"queue_s": max(0.0, now - sub),
                "prefill_s": 0.0, "decode_s": 0.0}
    a0, a1 = admitted
    return {"queue_s": max(0.0, a0 - sub),
            "prefill_s": a1 - a0, "decode_s": now - a1}


@dataclasses.dataclass
class _Running:
    req: Request
    slot: int
    tokens: List[int] = dataclasses.field(default_factory=list)
    first_token_time: Optional[float] = None
    # admission window (clock domain): prefill_s = admit_t1 - admit_t0,
    # decode_s runs from admit_t1 to the finish edge
    admit_t0: float = 0.0
    admit_t1: float = 0.0
    # admission order — the block-aware preemption victim key (youngest
    # admitted evicts first, vLLM-style LIFO)
    seq: int = 0
    # streaming state: rid-global offset where THIS attempt's tokens
    # start (= the in-scheduler preemption prefix length at admit), and
    # how many of st.tokens have already left as TokenChunks
    chunk_base: int = 0
    emitted: int = 0
    # chunk-admitted and still mid-prefill (engine.is_prefilling): the
    # slot holds blocks but is INACTIVE — the prefill pump drives it one
    # chunk per tick, decode rows skip it, preemption never picks it
    prefilling: bool = False


class Scheduler:
    """FIFO continuous-batching scheduler over one SlotEngine."""

    def __init__(self, engine: SlotEngine, *, clock=None, max_queue: int = 64,
                 metrics=None, fault_hook=None, tracer=None,
                 replica: int = 0, telemetry=None,
                 stream: bool = True, vtc=None) -> None:
        self.engine = engine
        self.clock = clock or MonotonicClock()
        self.max_queue = max_queue
        self.metrics = metrics
        # optional chaos hook (serve/faults.py FaultInjector): None in
        # production — the only cost then is one `is not None` per tick
        self.fault_hook = fault_hook
        # optional TraceRecorder (utils/trace.py); `replica` is this
        # scheduler's pid in the exported timeline. The engine keeps its
        # own tracer reference (set_tracer) for its dispatch lanes.
        self.tracer = tracer
        self.replica = replica
        # optional utils/telemetry.py exporter (anything with
        # on_completion): one streamed "flight" line per completion —
        # for SINGLE-replica serving. Behind a router, the router is the
        # telemetry owner (its merged flight records are the real ones).
        self.telemetry = telemetry
        self.queue: Deque[Request] = deque()
        self.running: Dict[int, _Running] = {}  # slot -> state
        self.completions: List[Completion] = []
        # streaming side channel: one TokenChunk per request per decode
        # burst plus one final chunk per completion, append-only and
        # watermark-consumed exactly like `completions`. `stream=False`
        # is the end-of-request-delivery baseline (the overhead bench's
        # control arm) — no chunks are ever built.
        self.stream = stream
        self.chunks: List[TokenChunk] = []
        self._chunk_seq: Dict[int, int] = {}  # rid -> next chunk seq
        self._admit_counter = 0
        # speculative decoding (serve/spec.py + engine.step_verify): a
        # spec-enabled engine carries a drafter; ticks where any slot
        # has a proposal dispatch the verify program instead of a
        # plain burst (both greedy-exact — the choice never shows in
        # the token stream). `_spec_k` also widens every admission's
        # position budget: verify grows a slot for the worst case
        # (spec_k + 1 positions) before acceptance is known.
        self._spec_k = (engine.config.spec_k
                        if getattr(engine, "drafter", None) is not None
                        else 0)
        # rid -> [drafted, accepted] cumulative across this request's
        # verify dispatches (rid-keyed, so preemption/readmission keeps
        # accumulating); popped into the completion's flight record
        self._spec_stats: Dict[int, list] = {}
        # rid -> prefix-cache matched tokens, cumulative across this
        # request's admits (a preempted continuation re-matches its own
        # earlier blocks); popped into the flight record the same way.
        # Only tracked for engines with a radix (last_prefix_hit set).
        self._prefix_hits: Dict[int, int] = {}
        # preempted-request resume state (PagedEngine block-aware
        # preemption): rid -> {"orig": the ORIGINAL request, "prefix":
        # tokens generated before the eviction, "ftt": their first-token
        # time}. The continuation re-prefills prompt+prefix; `_finish`
        # folds the prefix back so the client sees one completion.
        self._resume: Dict[int, dict] = {}
        # optional serve/fairshare.py VirtualTokenCounter: when set,
        # _admit serves the LEAST-SERVED tenant's earliest request
        # instead of strict FIFO, and this scheduler charges the
        # counters (prefill at admit, decode at finish). None (the
        # default) leaves every code path byte-identical to FIFO.
        self.vtc = vtc

    # ------------------------------------------------------------ intake
    def submit(self, req: Request) -> bool:
        """Enqueue; False = shed (queue at bound) or rejected (malformed).
        Both are completions too — the client gets a fast negative, not
        silence."""
        if req.arrival is None:
            req.arrival = self.clock.now()
        if req.trace_id is None:
            req.trace_id = f"r{req.rid}"
        if self.tracer is not None:
            # the head decision, made exactly once per trace_id: reuse
            # an upstream stamp (router / RPC seam) when present, roll
            # the deterministic hash otherwise. Unsampled requests'
            # spans stage until the tail verdict in _finish.
            req.sampled = self.tracer.begin_trace(req.trace_id,
                                                  req.sampled,
                                                  tenant=req.tenant)
        req.submitted = self.clock.now()
        if req.max_new_tokens < 1:
            # needed=0 would slip past every headroom guard and a
            # zero-token request would still emit one token — a fast
            # reject is the only sane answer
            self._finish(req, [], "rejected")
            return False
        if len(self.queue) >= self.max_queue:
            self._finish(req, [], "shed")
            return False
        self.queue.append(req)
        if self.vtc is not None:
            # register at the current service floor (VTC lift) — a
            # newly-seen tenant competes from here, not from an idle-
            # hours credit balance
            self.vtc.touch(req.tenant)
        if self.metrics:
            self.metrics.on_submit(self)
        return True

    # ------------------------------------------------------------ internals
    def _emit_chunk(self, rid: int, trace_id: Optional[str], start: int,
                    tokens: List[int], *, final: bool = False,
                    status: Optional[str] = None) -> None:
        """Append one TokenChunk (no-op with streaming off). `start` is
        the rid-GLOBAL token offset. The final chunk retires the rid's
        seq counter, so `_chunk_seq` stays O(in-flight)."""
        if not self.stream:
            return
        seq = self._chunk_seq.get(rid, 0)
        self._chunk_seq[rid] = seq + 1
        self.chunks.append(TokenChunk(
            rid=rid, trace_id=trace_id, seq=seq, start=start,
            tokens=list(tokens), t=self.clock.now(), final=final,
            status=status,
        ))
        if final:
            self._chunk_seq.pop(rid, None)
        emit = getattr(self.telemetry, "emit", None)
        if emit is not None:
            # single-replica serving (a TelemetryExporter attached
            # directly): per-chunk JSONL so tools/check_stream.py can
            # audit delivery offline. Behind a router, the router's
            # consumer-side stream events are the audited lines; worker
            # FlightStats has no emit and skips this branch.
            emit("chunk", trace_id=trace_id, rid=rid, seq=seq,
                 start=start, n=len(tokens), final=final, status=status,
                 # which decode dispatch produced these tokens — the
                 # flight-accounting hook that tells a stalled engine
                 # (burst stands still) from a starved request (bursts
                 # advance without it) inside a resume gap
                 burst=getattr(self.engine, "burst_seq", None))

    def _finish(self, req: Request, tokens: List[int], status: str,
                first_token_time: Optional[float] = None,
                admitted: Optional[tuple] = None,
                chunked: Optional[int] = None) -> Completion:
        now = self.clock.now()
        prior = self._resume.pop(req.rid, None)
        if prior is not None:
            # a continuation of a preempted request: the client asked
            # ONE question — fold the pre-eviction tokens (and their
            # first-token time) back into the single completion
            tokens = prior["prefix"] + tokens
            if prior["ftt"] is not None:
                first_token_time = prior["ftt"]
        if chunked is None:
            # not finishing from a running slot: everything this rid
            # ever streamed is its preemption prefix (queued shed /
            # timeout / stale continuation) or nothing (fresh request)
            chunked = len(prior["prefix"]) if prior is not None else 0
        if self.vtc is not None and tokens:
            # decode service lands at the terminal: each DELIVERED token
            # charges once, whatever preemption/readmission path
            # produced it (re-prefill work was charged as prefill at
            # each admit — both costs were actually incurred)
            self.vtc.charge(req.tenant, decode=len(tokens))
        # the terminal marker: whatever tokens have not streamed yet
        # ride out with it, so chunk delivery is complete exactly when
        # the completion exists (one final chunk per completion, even
        # for sheds/rejects — a typed end, never silence)
        self._emit_chunk(req.rid, req.trace_id, chunked,
                         tokens[chunked:], final=True, status=status)
        ttft = tpot = None
        if first_token_time is not None:
            ttft = first_token_time - req.arrival
            if len(tokens) > 1:
                tpot = (now - first_token_time) / (len(tokens) - 1)
        # flight record: phase breakdown of this attempt's latency;
        # anything before submit, and nothing else, lands in stall_s
        flight = _attempt_phases(req, now, admitted)
        total = now - req.arrival
        flight["stall_s"] = max(0.0, total - sum(flight.values()))
        flight["retries"] = flight["failovers"] = 0
        spec = self._spec_stats.pop(req.rid, None)
        if spec is not None:
            # after the stall_s residual — these are token counts, not
            # latency phases, and must not skew the phase sum
            flight["spec_drafted"] = spec[0]
            flight["spec_accepted"] = spec[1]
            if spec[0] > 0:
                flight["spec_accept_rate"] = spec[1] / spec[0]
        ph = self._prefix_hits.pop(req.rid, None)
        if ph is not None:
            # token count, not a latency phase — same placement rule as
            # the spec_* tallies above
            flight["prefix_hit_tokens"] = ph
        # prompt size rides the flight record so downstream cost
        # metering (serve/fairshare.py TenantLedger) can bill prefill
        # work without a back-pointer to the request
        flight["prompt_tokens"] = len(req.prompt)
        c = Completion(
            rid=req.rid, tokens=tokens, status=status,
            arrival=req.arrival, finish=now, ttft=ttft, tpot=tpot,
            flight=flight, trace_id=req.trace_id, tenant=req.tenant,
        )
        tr = self.tracer
        if tr is not None and tr.enabled:
            if admitted is None:
                # never admitted: its whole life here was the queue
                sub = (req.submitted if req.submitted is not None
                       else req.arrival)
                tr.record_async("queued", sub, now, trace_id=req.trace_id,
                                pid=self.replica)
            if status not in ("eos", "length"):
                tr.instant(status, trace_id=req.trace_id, pid=self.replica,
                           tid=ENGINE_LANE, rid=req.rid)
            tr.record_async(
                "request", req.arrival, now, trace_id=req.trace_id,
                pid=self.replica,
                attrs={"rid": req.rid, "status": status,
                       "tokens": len(tokens)},
            )
        if tr is not None:
            # tail verdict: promote the staged spans when a keep-rule
            # fires (bad status / slow / an anomaly marker already
            # promoted them), else discard as suppressed. The outcome
            # rides the completion so exemplars only cite kept traces.
            c.trace_sampled = tr.finish_trace(
                req.trace_id, status=status,
                latency_s=now - req.arrival)
        self.completions.append(c)
        if self.metrics:
            self.metrics.on_complete(c, self)
        if self.telemetry is not None:
            self.telemetry.on_completion(c)
        return c

    def _expire_queue(self) -> None:
        now = self.clock.now()
        kept: Deque[Request] = deque()
        for req in self.queue:
            if req.deadline is not None and now > req.deadline:
                self._finish(req, [], "timeout")
            else:
                kept.append(req)
        self.queue = kept

    # ------------------------------------------ preemption / readmission
    def _requeue_request(self, orig: Request, prompt: List[int],
                         max_new: int) -> Request:
        """Clone `orig` for a re-prefill attempt: same identity /
        arrival / deadline / trace (one request, one timeline), new
        prompt+budget, and `submitted` stamped NOW — without the stamp
        the flight record books the whole prior attempt as queue_s
        (Request.submitted exists exactly to prevent that)."""
        creq = Request(
            rid=orig.rid, prompt=prompt, max_new_tokens=max_new,
            deadline=orig.deadline, seed=orig.seed, arrival=orig.arrival,
            priority=orig.priority, trace_id=orig.trace_id,
            sampled=orig.sampled, tenant=orig.tenant,
            temperature=orig.temperature, top_k=orig.top_k,
            top_p=orig.top_p,
        )
        creq.submitted = self.clock.now()
        return creq

    def _continuation(self, st: _Running) -> Request:
        """Build the re-prefill request for a preempted running entry:
        prompt + tokens-generated-so-far, the remaining token budget,
        the ORIGINAL arrival/deadline/trace_id (one request, one
        timeline). Falls back to regenerating from the original prompt
        when prompt+prefix outgrows the engine (greedy reproduces the
        same tokens — the router's failover makes the same trade)."""
        req = st.req
        prior = self._resume.pop(req.rid, None)
        orig = prior["orig"] if prior else req
        prefix = (prior["prefix"] if prior else []) + st.tokens
        ftt = (prior["ftt"] if prior and prior["ftt"] is not None
               else st.first_token_time)
        new_prompt = list(orig.prompt) + prefix
        remaining = orig.max_new_tokens - len(prefix)
        needed = self._needed_positions(remaining)
        if prefix and self.engine.admit_gate(
                len(new_prompt), needed, prompt=new_prompt) == "never":
            prefix, ftt = [], None
            new_prompt = list(orig.prompt)
            remaining = orig.max_new_tokens
        if prefix:
            self._resume[req.rid] = {
                "orig": orig, "prefix": prefix, "ftt": ftt,
            }
        creq = self._requeue_request(orig, new_prompt, remaining)
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.instant("preempted", trace_id=orig.trace_id,
                       pid=self.replica, tid=ENGINE_LANE, rid=orig.rid,
                       tokens_salvaged=len(prefix))
        return creq

    def _drain_preempted(self) -> None:
        """Requeue requests the ENGINE evicted during step_burst (paged
        growth/CoW exhaustion): they re-enter at the FRONT and
        re-prefill as room returns. No-op for engines without
        preemption (SlotEngine)."""
        take = getattr(self.engine, "take_preempted", None)
        if take is None:
            return
        for slot in take():
            st = self.running.pop(slot, None)
            if st is not None:
                self.queue.appendleft(self._continuation(st))

    def _preempt_victim_for(self, req: Request) -> Optional[Request]:
        """Admission-pressure preemption: evict the YOUNGEST-admitted
        running request so `req` (the blocked queue head) can take its
        blocks — but only when `req` arrived strictly EARLIER than the
        victim. Preemption then only ever flows older-over-younger, so
        readmission cascades terminate (a victim can never win its
        blocks back from the request that took them). Returns the
        victim's continuation request, or None when no fair victim
        exists (the head just waits for releases). UNFAIR entries are
        skipped, not a reason to bail: a readmitted continuation
        carries a fresh (high) admission seq but its ORIGINAL arrival,
        and it must not shield the genuinely-younger runners behind
        it."""
        eng = self.engine
        if not hasattr(eng, "preempt") or not self.running:
            return None
        key = ((req.arrival or 0.0), req.rid)
        # mid-prefill slots are not preemptable (the engine raises on
        # inactive slots; their progress is chunks, not salvageable
        # tokens) — skip them like the engine's own victim search does
        fair = [(st.seq, slot) for slot, st in self.running.items()
                if not st.prefilling
                and key < ((st.req.arrival or 0.0), st.req.rid)]
        if not fair:
            return None
        slot = max(fair)[1]
        st = self.running[slot]
        eng.preempt(slot)
        eng.take_preempted()  # consumed here, not by the post-burst drain
        del self.running[slot]
        return self._continuation(st)

    def _preemption_can_help(self, req: Request) -> bool:
        """Feasibility before the first eviction: even taking EVERY fair
        (strictly-younger-arrival) victim's blocks is an upper bound on
        what preemption surfaces — when that still cannot admit the
        head, evicting anyone is pure churn (victims lose their decode
        progress to re-prefill, the head stays blocked), so nobody is
        touched and the head waits for releases instead."""
        eng = self.engine
        if not hasattr(eng, "preempt_headroom"):
            return True
        key = ((req.arrival or 0.0), req.rid)
        fair = [s for s, st in self.running.items()
                if not st.prefilling
                and key < ((st.req.arrival or 0.0), st.req.rid)]
        return eng.preempt_headroom(fair, len(req.prompt),
                                    prompt=req.prompt)

    def _needed_positions(self, max_new: int) -> int:
        """A request's decode-position budget: burst-granular (a request
        finishing mid-burst still rides to the burst boundary), plus —
        with speculation on — the verify program's worst-case slack:
        `step_verify` grows a slot for spec_k + 1 positions before
        knowing how much of the draft the model accepts, so the
        admit-time block budget must cover the final dispatch's
        overshoot (the rejected tail's blocks come straight back)."""
        burst = self.engine.config.decode_burst
        needed = -(-max(max_new, 1) // burst) * burst
        if self._spec_k:
            needed += self._spec_k + 1
        return needed

    def _rotate_fair_head(self) -> None:
        """Weighted-fair head pick (serve/fairshare.py, vtc set):
        rotate the LEAST-SERVED tenant's earliest request to the queue
        head. Within a tenant order stays FIFO; a tie on service breaks
        toward the earlier queue position, so equal-service tenants
        degrade to plain arrival order. Everything downstream —
        admission gates, preemption, the insert(1) staging — still
        operates on the head, unchanged. No-op without a vtc: the
        default path stays byte-identical to FIFO."""
        if self.vtc is None or len(self.queue) <= 1:
            return
        firsts: Dict[str, int] = {}
        for i, r in enumerate(self.queue):
            name = r.tenant if r.tenant is not None else "default"
            if name not in firsts:
                firsts[name] = i
        if len(firsts) <= 1:
            return
        i = min(firsts.items(),
                key=lambda kv: (self.vtc.service(kv[0]), kv[1]))[1]
        if i:
            req = self.queue[i]
            del self.queue[i]
            self.queue.appendleft(req)

    def _admit(self) -> None:
        eng = self.engine
        tr = self.tracer
        while self.queue and eng.num_free > 0:
            self._rotate_fair_head()
            req = self.queue[0]
            needed = self._needed_positions(req.max_new_tokens)
            # memory policy is the ENGINE's: the slot engine gates on
            # global cursor headroom (make_room = drain + epoch rewind),
            # the paged engine on free + prefix-cache-evictable blocks
            # (pages free per-request at release; make_room ages out
            # cached prefixes; block-aware preemption evicts young
            # runners for older blocked work). The scheduler only
            # distinguishes can't-yet from can't-ever — and enforces
            # the arrival-order fairness preemption needs.
            gate = eng.admit_gate(len(req.prompt), needed,
                                  prompt=req.prompt)
            if gate == "later" and eng.make_room(len(req.prompt), needed,
                                                 prompt=req.prompt):
                gate = eng.admit_gate(len(req.prompt), needed,
                                      prompt=req.prompt)
            if gate == "later" and self._preemption_can_help(req):
                staged: List[Request] = []
                while gate == "later":
                    creq = self._preempt_victim_for(req)
                    if creq is None:
                        break
                    staged.append(creq)
                    gate = eng.admit_gate(len(req.prompt), needed,
                                          prompt=req.prompt)
                # victims re-enter BEHIND the head (they are strictly
                # younger by arrival — queue order stays arrival order).
                # staged is in EVICTION order (descending admission
                # seq), which is NOT arrival order when a victim is a
                # readmitted continuation (fresh high seq, ORIGINAL old
                # arrival) — sort by arrival descending so each
                # insert(1) pushes the previous back and the oldest
                # arrival lands first behind the head.
                staged.sort(key=lambda r: ((r.arrival or 0.0), r.rid),
                            reverse=True)
                for creq in staged:
                    self.queue.insert(1, creq)
            if gate == "never":
                self.queue.popleft()
                prior = self._resume.pop(req.rid, None)
                if prior is not None:
                    # a preempted request's continuation went STALE in
                    # the queue: the warm prefix it was sized against
                    # aged out of the cache, and prompt+tokens-so-far
                    # no longer fits a bucket. Retry from the ORIGINAL
                    # prompt (greedy/seeded decode reproduces the lost
                    # tokens — the trade _continuation already makes at
                    # build time) instead of rejecting a servable
                    # request. The _resume entry is consumed, so a
                    # genuine "never" on the retry still rejects.
                    orig = prior["orig"]
                    if tr is not None and tr.enabled:
                        tr.instant("stale_retry", trace_id=req.trace_id,
                                   pid=self.replica, tid=ENGINE_LANE,
                                   rid=req.rid,
                                   tokens_dropped=len(prior["prefix"]))
                    self.queue.appendleft(self._requeue_request(
                        orig, list(orig.prompt), orig.max_new_tokens))
                    continue
                if tr is not None and tr.enabled:
                    tr.instant("admit_never", trace_id=req.trace_id,
                               pid=self.replica, tid=ENGINE_LANE,
                               prompt_len=len(req.prompt), needed=needed)
                self._finish(req, [], "rejected")
                continue
            if gate == "later":
                # memory frees as running requests release; one instant
                # per blocked tick (the ring buffer bounds the flood)
                if tr is not None and tr.enabled:
                    tr.instant("admit_blocked", trace_id=req.trace_id,
                               pid=self.replica, tid=ENGINE_LANE,
                               queue=len(self.queue))
                break
            self.queue.popleft()
            if self.fault_hook is not None \
                    and self.fault_hook.take_admit_fault():
                # injected transient admission failure (OOM-at-admit
                # class): an "error" completion, so a router retries it
                # on another replica instead of the client seeing silence
                self._finish(req, [], "error")
                continue
            t_admit0 = self.clock.now()
            admit_kw = {}
            if (req.temperature is not None or req.top_k is not None
                    or req.top_p is not None):
                # only when the request actually overrides — engines
                # (and test fakes) without the kwarg stay untouched
                admit_kw["sampling"] = (req.temperature, req.top_k,
                                        req.top_p)
            try:
                slot = eng.admit(req.prompt, seed=req.seed,
                                 max_positions=needed,
                                 trace_id=req.trace_id, **admit_kw)
            except ValueError:
                # sampling overrides on an engine without
                # per_slot_sampling (or a shape the gate missed): a
                # typed fast negative, not a crashed tick
                self._finish(req, [], "rejected")
                continue
            t_admit1 = self.clock.now()
            hit = getattr(eng, "last_prefix_hit", None)
            if hit is not None:
                self._prefix_hits[req.rid] = (
                    self._prefix_hits.get(req.rid, 0) + hit
                )
            if self.vtc is not None:
                # prefill service at admit (cache-warm tokens are free:
                # the engine never recomputed them) — immediate, so the
                # NEXT head pick already sees this tenant's spend
                self.vtc.charge(req.tenant, prefill=max(
                    0, len(req.prompt) - (hit or 0)))
            if tr is not None and tr.enabled:
                sub = req.submitted if req.submitted is not None \
                    else req.arrival
                tr.record_async("queued", sub, t_admit0,
                                trace_id=req.trace_id, pid=self.replica,
                                attrs={"slot": slot})
            self._admit_counter += 1
            prior = self._resume.get(req.rid)
            self.running[slot] = _Running(
                req=req, slot=slot, admit_t0=t_admit0, admit_t1=t_admit1,
                seq=self._admit_counter,
                # a preempted continuation's chunks continue the rid's
                # global token offsets after the already-streamed prefix
                chunk_base=len(prior["prefix"]) if prior else 0,
                prefilling=bool(getattr(
                    eng, "is_prefilling", lambda s: False)(slot)),
            )

    def _prefill_pump(self) -> None:
        """Drive ONE prefill chunk per mid-prefill slot per tick —
        Sarathi-style interleaving: a long cold prompt shares every
        tick with the running decode burst instead of monopolizing one,
        so running streams see at most one chunk's forward of added
        inter-token latency and TTFT jitter stops tracking the longest
        admit. Deadline expiry mid-prefill is a "timeout" finish (the
        blocks come back); a chunk the pool cannot cover even after
        preemption releases the slot and requeues the request at the
        front, like any admission failure."""
        eng = self.engine
        for slot, st in list(self.running.items()):
            if not st.prefilling:
                continue
            now = self.clock.now()
            if st.req.deadline is not None and now > st.req.deadline:
                del self.running[slot]
                eng.release(slot)
                self._finish(st.req, [], "timeout",
                             admitted=(st.admit_t0, now))
                continue
            try:
                done = eng.prefill_step(slot)
            except RuntimeError:
                del self.running[slot]
                eng.release(slot)
                self.queue.appendleft(self._continuation(st))
                continue
            self.clock.tick()
            if done:
                # the slot just went active: prefill ends HERE for the
                # flight record, and the next burst decodes it with
                # everyone else
                st.prefilling = False
                st.admit_t1 = self.clock.now()
        # chunk growth may have preempted active runners
        # (_acquire_decode inside prefill_step) — requeue them before
        # the burst maps token rows
        self._drain_preempted()

    # ------------------------------------------------------------ the tick
    def step(self) -> List[Completion]:
        """One tick: expire -> admit -> prefill chunks -> decode ->
        release. Returns the completions finalized during this tick.
        May raise faults.ReplicaCrashed when a chaos plan kills this
        replica."""
        if self.fault_hook is not None:
            self.fault_hook.on_tick(self)
        before = len(self.completions)
        self._expire_queue()
        self._admit()
        self._prefill_pump()
        if any(not st.prefilling for st in self.running.values()):
            eng = self.engine
            counts = None
            drafted = None
            if self._spec_k:
                drafts, draft_lens, any_drafted = eng.propose_drafts()
                if any_drafted:
                    drafted = (drafts, draft_lens)
            if drafted is None:
                # no slot has a proposal this tick (or speculation is
                # off): plain burst — greedy-identical to a verify of
                # empty drafts, minus the wasted window forward
                burst = eng.step_burst()      # (K, max_slots)
                finite = eng.last_finite      # (K, max_slots)
            else:
                # verify dispatch: rows are the accepted run + one
                # correction token; row r of a slot is real iff
                # r < counts[slot]
                burst, counts, finite = eng.step_verify(*drafted)
            # block-aware preemption: slots the engine evicted BEFORE
            # this dispatch produced no tokens this burst — requeue
            # their requests (front) before mapping token rows
            self._drain_preempted()
            if counts is not None:
                # accept accounting BEFORE the row loop, so a request
                # finishing mid-run still books its last dispatch.
                # Every slot still running was active at dispatch, so
                # counts >= 1 (accepted = counts - 1).
                for slot, st in self.running.items():
                    if st.prefilling:
                        continue  # inactive at dispatch: counts[slot]=0
                    stats = self._spec_stats.setdefault(
                        st.req.rid, [0, 0])
                    stats[0] += int(drafted[1][slot])
                    stats[1] += int(counts[slot]) - 1
            eos = self.engine.config.eos_id
            for k, row in enumerate(burst):
                if not self.running:
                    break  # the rest of the burst is free-slot padding
                if counts is not None and all(
                        k >= int(counts[s]) for s in self.running):
                    break  # every remaining run ended before this row
                self.clock.tick()
                now = self.clock.now()
                for slot, st in list(self.running.items()):
                    if st.prefilling:
                        continue  # inactive at dispatch: rows are pads
                    if counts is not None and k >= int(counts[slot]):
                        continue  # this slot's verified run was shorter
                    if not finite[k, slot]:
                        # this row's token was sampled from non-finite
                        # logits: poison ONE request, not the batch — the
                        # tokens produced so far are valid (finite when
                        # sampled), so a router can resume from them
                        del self.running[slot]
                        self.engine.release(slot)
                        self._finish(
                            st.req, st.tokens, "error",
                            st.first_token_time,
                            admitted=(st.admit_t0, st.admit_t1),
                            chunked=st.chunk_base + st.emitted,
                        )
                        continue
                    tok = int(row[slot])
                    st.tokens.append(tok)
                    if st.first_token_time is None:
                        st.first_token_time = now
                    done_status = None
                    if eos is not None and tok == eos:
                        done_status = "eos"
                    elif len(st.tokens) >= st.req.max_new_tokens:
                        done_status = "length"
                    elif (st.req.deadline is not None
                          and now > st.req.deadline):
                        done_status = "timeout"
                    if done_status:
                        # released mid-burst: later rows of this burst
                        # no longer map to this request (its surplus
                        # tokens are discarded with it)
                        del self.running[slot]
                        self.engine.release(slot)
                        self._finish(
                            st.req, st.tokens, done_status,
                            st.first_token_time,
                            admitted=(st.admit_t0, st.admit_t1),
                            chunked=st.chunk_base + st.emitted,
                        )
            if self.stream:
                # one TokenChunk per still-running request per burst:
                # the tokens this tick produced, stamped with their
                # rid-global offsets. Finished requests already left
                # through their final chunk in _finish.
                for st in self.running.values():
                    if len(st.tokens) > st.emitted:
                        self._emit_chunk(
                            st.req.rid, st.req.trace_id,
                            st.chunk_base + st.emitted,
                            st.tokens[st.emitted:],
                        )
                        st.emitted = len(st.tokens)
        if self.metrics:
            self.metrics.on_tick(self)
        return self.completions[before:]

    # ------------------------------------------------- fleet operations
    def shed_queued(self, predicate) -> List[Request]:
        """Shed queued (not yet admitted) requests matching `predicate`
        — the brown-out lever: the router drops low-priority waiters
        when fleet occupancy crosses its threshold. Each shed is a
        normal "shed" completion (fast negative, not silence); the shed
        requests are returned so the router can finalize them with the
        right reason."""
        kept: Deque[Request] = deque()
        shed: List[Request] = []
        for req in self.queue:
            if predicate(req):
                self._finish(req, [], "shed")
                shed.append(req)
            else:
                kept.append(req)
        self.queue = kept
        return shed

    def inflight_snapshot(self) -> List[tuple]:
        """Non-destructive view of every queued and running request:
        (request, tokens_so_far, first_token_time, phases) — the same
        tuples `evacuate` harvests, WITHOUT clearing anything. The
        cross-process worker (serve/worker.py) ships this per poll so
        the router always holds a recent salvage point: when the worker
        is later SIGKILLed there is no scheduler left to evacuate, and
        the last snapshot is what failover re-admits on a survivor
        (prompt + tokens-so-far, token-identical under greedy)."""
        now = self.clock.now()
        out = []
        for st in self.running.values():
            prior = self._resume.get(st.req.rid)
            req, toks, ftt = st.req, st.tokens, st.first_token_time
            if prior is not None:
                # a running CONTINUATION of a preempted request: hand
                # the caller the ORIGINAL request with all tokens so
                # far, not the synthetic prompt+prefix one
                req = prior["orig"]
                toks = prior["prefix"] + toks
                ftt = prior["ftt"] if prior["ftt"] is not None else ftt
            out.append((req, list(toks), ftt,
                        _attempt_phases(st.req, now,
                                        (st.admit_t0, st.admit_t1))))
        for req in self.queue:
            prior = self._resume.get(req.rid)
            if prior is not None:
                out.append((prior["orig"], list(prior["prefix"]),
                            prior["ftt"],
                            _attempt_phases(req, now, None)))
            else:
                out.append((req, [], None, _attempt_phases(req, now, None)))
        return out

    def evacuate(self) -> List[tuple]:
        """Pull every queued and in-flight request off this scheduler —
        the failover harvest after a crash. Returns the
        `inflight_snapshot` tuples; tokens_so_far were already read
        back to the host before the crash, so the router can re-admit
        prompt+tokens on a surviving replica. `phases` is the attempt's
        flight-record fragment (queue_s / prefill_s / decode_s up to
        the evacuation edge) — no Completion is ever appended for an
        evacuated attempt, so without this the pre-crash work would be
        misreported as stall time. Touches no device state (the replica
        may be gone); `restart()` on the handle resets the engine when
        the replica comes back."""
        out = self.inflight_snapshot()
        # every live rid is in queue/running, so their _resume entries
        # (already folded into the snapshot) go with them — and their
        # chunk seq counters: evacuated attempts never reach a final
        # chunk, and the router re-dispatches under a fresh attempt.
        # Accept stats die with the attempt too: the surviving
        # replica's verify dispatches start the rid's count fresh.
        self._resume.clear()
        self.running.clear()
        self.queue.clear()
        self._chunk_seq.clear()
        self._spec_stats.clear()
        self._prefix_hits.clear()
        return out

    @property
    def idle(self) -> bool:
        return not self.queue and not self.running

    def run_until_idle(self, max_ticks: int = 100_000) -> List[Completion]:
        """Drive ticks until queue and slots drain (tests + CLI serving)."""
        for _ in range(max_ticks):
            if self.idle:
                return self.completions
            self.step()
        raise RuntimeError(f"not idle after {max_ticks} ticks")
