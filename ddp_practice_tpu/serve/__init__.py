"""serve/: TPU-native continuous-batching inference engine + fleet.

Layers (each its own module, composable and separately testable):

- kv_slots.py  — slot-based KV-cache pool: fixed `(max_slots, max_len)`
  cache, left-aligned admission at a shared write cursor, whole-row
  scatter on admit, free-list slot reuse;
- kv_pages.py  — PAGED KV-cache pool (vLLM-style): fixed-size blocks,
  host block allocator + per-slot device page tables, slot-local
  positions — no shared clock, per-page release, contexts past max_len;
- engine.py    — SlotEngine/PagedEngine: bucketed jitted prefill-admit
  + one jitted batched decode step; static shapes, so batch composition
  churns with zero recompiles; per-slot finite-logits flag contains a
  NaN to one request; one interface (admit_gate/admit/step_burst/
  release) over both memory layouts;
- spec.py      — speculative decoding drafts WITHOUT a draft model:
  DraftSource interface + the n-gram prompt-lookup drafter (host-side
  suffix match over prompt+generated tokens); the paged engine verifies
  k drafted tokens in ONE jitted forward (the s>1 paged-prefill path)
  with exact greedy acceptance and block-aware KV rollback —
  token-identical to plain decoding, fewer sequential steps;
- scheduler.py — FIFO queue, admission control (bounded queue sheds),
  per-request deadlines, EOS/length release, injectable clock
  (FakeClock for deterministic CPU tests) and fault hook;
- faults.py    — seeded, JSON-serializable FaultPlan (crash / latency /
  nan_logits / admit_fail) driving deterministic chaos tests and
  goodput-under-faults benches;
- health.py    — per-replica HEALTHY/DEGRADED/DEAD state machine with a
  consecutive-failure circuit breaker and backoff half-open probes;
- slo.py       — declarative SLO targets (TTFT/TPOT p99, error rate,
  availability) evaluated as multi-window burn rates; alerts feed the
  router's brown-out, the telemetry stream, and PUSH sinks
  (AlertSinks: command/webhook/jsonl with retry backoff + a dead-sink
  breaker; FleetAlerts raises the same edges for dead/stale workers)
  (utils/telemetry.py exports the plane: JSONL streaming + /metrics
  /healthz /flight HTTP scrape endpoints; tools/check_slo.py is the
  offline verdict);
- router.py    — fault-tolerant least-loaded dispatch over N replicas:
  bounded retries with backoff+jitter, crash failover that migrates
  in-flight requests (prompt + tokens-so-far re-prefill,
  token-identical under greedy), brown-out degradation. The router
  drives a NARROW replica interface (submit/step/poll/evacuate +
  observables) — in-process handles and worker processes are
  indistinguishable to it;
- rpc.py       — the transport seam under that interface:
  length-prefixed JSON frames over localhost TCP, idempotent ops,
  per-call timeouts, shared-backoff reconnects, and a push-stream
  mode (the worker pushes completion/heartbeat snapshots; the
  router select()s on the stream fds — no polling in steady state);
- worker.py    — one replica as a real OS PROCESS: own single-process
  jax runtime, Scheduler+Slot/PagedEngine built from a JSON
  WorkerSpec, warmed before its WORKER_READY line, serving the RPC
  seam plus its own /metrics /healthz /flight endpoints;
- supervisor.py— worker lifecycles: spawn/waitpid, restart with
  exponential backoff + a restart-budget circuit breaker, graceful
  drain, orphan reaping (atexit + pytest fixture), the router-facing
  RemoteReplicaHandle (salvage-point failover, stale-heartbeat
  SIGKILL), and the fleet builder / telemetry federation glue
  (utils/telemetry.py ScrapeFederator, tools/check_fleet.py verdict);
- metrics.py   — TTFT/TPOT/queue-depth/occupancy per replica plus the
  fleet counters (retries, failovers, sheds-by-reason, breaker state,
  brown-out), emitted through the process-0 gate (utils/metrics.py
  render_text() serves the same registry as Prometheus exposition);
  request-lifecycle SPANS live in utils/trace.py: scheduler/engines/
  router all take an optional TraceRecorder (`--trace-out` exports
  Chrome trace JSON; tools/check_traces.py validates it), and every
  Completion carries a queue/prefill/decode/stall flight record;
- bench.py     — serve_bench: one Poisson trace through the continuous
  engine, the static-batch baseline, and (--replicas) the router fleet
  with optional --fault-plan goodput runs (BENCHMARKS.md records the
  curves); also the `cli.py serve` entry point;
- frontdoor.py — the HTTP/SSE wire surface over Router.stream: POST
  /v1/generate streams the typed tokens/resumed/end events as SSE
  frames (sse.py codec, shared by server and client), per-tenant
  admission at the door (admission.py token buckets + concurrency
  caps), auth/validation hooks, bounded-buffer slow-consumer shedding,
  and a SIGTERM-shaped graceful drain;
- fairshare.py — the tenant QoS ledgers: VTC-style weighted-fair
  service counters (least-served drives the scheduler's fair head
  pick, most-over-served drives the door's "fairness" refusal — both
  behind flags that degrade byte-identically to FIFO when off),
  per-tenant cost metering (the /tenants endpoint + fleet federation),
  and Jain's fairness index; slo.py's TenantSLORegistry gives each
  tenant its own error budget so a hostile tenant's burn pages as ITS
  alert and scopes the brown-out to ITS work;
- workload.py  — deterministic multi-tenant workload plans (the QoS
  lab): per-tenant Poisson/bursty/diurnal arrivals, heavy-tailed
  lengths, multi-turn sessions, a hostile marker — JSON-serializable
  and byte-replayable, judged offline by tools/check_qos.py.
"""

from ddp_practice_tpu.serve.admission import (
    AdmissionController,
    TenantPolicy,
)

from ddp_practice_tpu.serve.fairshare import (
    TenantLedger,
    VirtualTokenCounter,
    federate_tenant_reports,
    jains_index,
)
from ddp_practice_tpu.serve.engine import (
    EngineConfig,
    PagedEngine,
    SlotEngine,
)
from ddp_practice_tpu.serve.faults import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
    ReplicaCrashed,
)
from ddp_practice_tpu.serve.health import (
    BreakerConfig,
    CircuitBreaker,
    HealthState,
    ReplicaHealth,
)
from ddp_practice_tpu.serve.kv_pages import (
    BlockAllocator,
    RadixPrefixCache,
)
from ddp_practice_tpu.serve.frontdoor import (
    Frontdoor,
    FrontdoorConfig,
    RouterDriver,
    sse_request,
)
from ddp_practice_tpu.serve.kv_slots import SlotAllocator
from ddp_practice_tpu.serve.metrics import (
    FrontdoorMetrics,
    RouterMetrics,
    ServeMetrics,
)
from ddp_practice_tpu.serve.router import (
    Router,
    RouterConfig,
    make_router,
)
from ddp_practice_tpu.serve.scheduler import (
    Completion,
    FakeClock,
    MonotonicClock,
    Request,
    Scheduler,
)
from ddp_practice_tpu.serve.rpc import (
    RpcClient,
    RpcError,
    RpcServer,
    RpcTimeout,
)
from ddp_practice_tpu.serve.spec import (
    DraftSource,
    PromptLookupDraft,
)
from ddp_practice_tpu.serve.slo import (
    AlertSinks,
    AlertSinkSpec,
    FleetAlerts,
    SLOConfig,
    SLOWatchdog,
    TenantSLORegistry,
)
from ddp_practice_tpu.serve.supervisor import (
    RemoteReplicaHandle,
    Supervisor,
    SupervisorConfig,
    make_fleet_router,
)
from ddp_practice_tpu.serve.worker import WorkerSpec
from ddp_practice_tpu.serve.workload import TenantSpec, WorkloadPlan

__all__ = [
    "AdmissionController",
    "AlertSinkSpec",
    "AlertSinks",
    "BlockAllocator",
    "BreakerConfig",
    "CircuitBreaker",
    "Completion",
    "DraftSource",
    "FleetAlerts",
    "EngineConfig",
    "FakeClock",
    "FaultInjector",
    "FaultPlan",
    "FaultSpec",
    "Frontdoor",
    "FrontdoorConfig",
    "FrontdoorMetrics",
    "HealthState",
    "MonotonicClock",
    "PagedEngine",
    "PromptLookupDraft",
    "RadixPrefixCache",
    "RemoteReplicaHandle",
    "ReplicaCrashed",
    "ReplicaHealth",
    "Request",
    "Router",
    "RouterConfig",
    "RouterDriver",
    "RouterMetrics",
    "RpcClient",
    "RpcError",
    "RpcServer",
    "RpcTimeout",
    "SLOConfig",
    "SLOWatchdog",
    "Scheduler",
    "ServeMetrics",
    "SlotAllocator",
    "SlotEngine",
    "Supervisor",
    "SupervisorConfig",
    "TenantLedger",
    "TenantPolicy",
    "TenantSLORegistry",
    "TenantSpec",
    "VirtualTokenCounter",
    "WorkerSpec",
    "WorkloadPlan",
    "federate_tenant_reports",
    "jains_index",
    "make_fleet_router",
    "make_router",
    "sse_request",
]
