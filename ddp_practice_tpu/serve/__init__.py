"""serve/: TPU-native continuous-batching inference engine.

Layers (each its own module, composable and separately testable):

- kv_slots.py  — slot-based KV-cache pool: fixed `(max_slots, max_len)`
  cache, left-aligned admission at a shared write cursor, whole-row
  scatter on admit, free-list slot reuse;
- engine.py    — SlotEngine: bucketed jitted prefill-admit + one jitted
  batched decode step; static shapes, so batch composition churns with
  zero recompiles;
- scheduler.py — FIFO queue, admission control (bounded queue sheds),
  per-request deadlines, EOS/length release, injectable clock
  (FakeClock for deterministic CPU tests);
- metrics.py   — TTFT/TPOT/queue-depth/occupancy/tokens-per-sec over the
  utils metrics registry, emitted through the process-0 gate;
- bench.py     — serve_bench: one Poisson trace through the continuous
  engine and the static-batch baseline (BENCHMARKS.md records the
  curves); also the `cli.py serve` entry point.
"""

from ddp_practice_tpu.serve.engine import EngineConfig, SlotEngine
from ddp_practice_tpu.serve.kv_slots import SlotAllocator
from ddp_practice_tpu.serve.metrics import ServeMetrics
from ddp_practice_tpu.serve.scheduler import (
    Completion,
    FakeClock,
    MonotonicClock,
    Request,
    Scheduler,
)

__all__ = [
    "Completion",
    "EngineConfig",
    "FakeClock",
    "MonotonicClock",
    "Request",
    "Scheduler",
    "ServeMetrics",
    "SlotAllocator",
    "SlotEngine",
]
