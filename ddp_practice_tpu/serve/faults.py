"""Deterministic fault injection for the serving fleet.

Fault tolerance that is only exercised by real outages is untested code.
This module makes failures a first-class, REPLAYABLE input: a FaultPlan
is a list of (replica, tick, kind) events, serialized as JSON so the
same plan drives a pytest chaos trace, a `cli.py serve --fault-plan`
run, and a goodput-under-faults bench row identically.

Kinds (each one real failure the fleet must survive):

- ``crash``      — the replica process dies at tick N (raises
  ReplicaCrashed out of Scheduler.step). `down_s` > 0 makes it
  restartable after that much clock time (the router's half-open probe
  finds it alive again); `down_s` 0 = gone for good.
- ``latency``    — one tick stalls `delay_s` (a GC pause, a preempted
  host, a slow collective): virtual clocks advance, real clocks sleep.
- ``nan_logits`` — slot `slot`'s next sampling input is poisoned with
  NaN (the numerical failure a bf16 overflow produces). The engine's
  per-slot finite-logits flag (serve/engine.py) must contain it to that
  one request.
- ``admit_fail`` — the next admission attempt AT OR AFTER this tick
  fails (OOM / transient allocator error): the failure is armed at the
  planned tick and STICKY until an admission actually consumes it, so a
  plan cannot silently miss because the queue happened to be empty that
  tick. The scheduler finishes the victim with status "error" and the
  router retries it elsewhere.
- ``kill`` — a REAL signal (`sig`: SIGKILL / SIGSTOP / SIGTERM) to a
  live worker OS process at `at_s` clock seconds into the run. Unlike
  the simulated kinds above, this one is not injected into a
  scheduler: the fleet-side `FleetFaultDriver` delivers it through the
  supervisor (serve/supervisor.py) to the replica's current pid. The
  sim `crash` path stays for FakeClock determinism; `kill` is the one
  that proves the failover story against actual process death
  (SIGKILL: no goodbye, in-flight decode lost; SIGSTOP: the process is
  alive but silent — the stale-heartbeat detection path).

Wiring: the injector is an optional `fault_hook` on Scheduler — one
`is not None` check per tick when unset, so the production path pays
nothing. Ticks are per-replica scheduler ticks (deterministic under
FakeClock); crash windows are measured in clock seconds so a downed
replica's recovery interacts with the breaker's probe backoff. ``kill``
specs are ignored by `injector()` — they target processes, not
schedulers — and fire from `FleetFaultDriver.poll` instead.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import List, Optional, Sequence


class ReplicaCrashed(RuntimeError):
    """Raised out of Scheduler.step when an injected crash fires."""


_KINDS = ("crash", "latency", "nan_logits", "admit_fail", "kill")
_SIGNALS = ("SIGKILL", "SIGSTOP", "SIGTERM")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str
    tick: int = 1            # per-replica scheduler tick (1-based);
    #                          unused by "kill" (which fires on at_s)
    replica: int = 0
    slot: int = 0            # nan_logits: which slot to poison
    delay_s: float = 0.0     # latency: stall length
    down_s: float = 0.0      # crash: clock time until probeable again
    #                          (0 = permanent)
    at_s: float = 0.0        # kill: seconds into the run to deliver
    sig: str = "SIGKILL"     # kill: which signal

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {_KINDS}")
        if self.tick < 1:
            raise ValueError("tick is 1-based (first Scheduler.step)")
        if self.kind == "kill" and self.sig not in _SIGNALS:
            raise ValueError(f"kill signal must be one of {_SIGNALS}, "
                             f"got {self.sig!r}")


class FaultPlan:
    """An ordered, serializable set of FaultSpecs for a whole fleet."""

    def __init__(self, faults: Sequence[FaultSpec] = ()) -> None:
        self.faults: List[FaultSpec] = list(faults)

    # --------------------------------------------------------------- json
    @classmethod
    def from_json(cls, src: str) -> "FaultPlan":
        """Parse a plan from a JSON string or a path to a JSON file.

        Schema: {"faults": [{"kind": ..., "tick": ..., "replica": ...,
        ...}]} — or a bare list of fault objects.
        """
        text = src
        if not src.lstrip().startswith(("{", "[")):
            # not inline JSON: it must be a file path — a missing file is
            # a missing file, not "malformed JSON" (a mistyped path fed
            # to json.loads would die with a misleading decode error)
            if not os.path.exists(src):
                raise FileNotFoundError(
                    f"fault plan {src!r}: not inline JSON and no such file"
                )
            with open(src) as f:
                text = f.read()
        data = json.loads(text)
        if isinstance(data, dict):
            data = data.get("faults", [])
        return cls([FaultSpec(**item) for item in data])

    def to_json(self) -> str:
        return json.dumps(
            {"faults": [dataclasses.asdict(f) for f in self.faults]}
        )

    # ----------------------------------------------------------- wiring
    def injector(self, replica: int) -> Optional["FaultInjector"]:
        """The per-replica hook, or None (= zero scheduler overhead)
        when no fault in the plan targets this replica. ``kill`` specs
        are excluded — they are delivered to OS processes by the
        fleet-side FleetFaultDriver, not raised inside a scheduler."""
        mine = [f for f in self.faults
                if f.replica == replica and f.kind != "kill"]
        return FaultInjector(mine) if mine else None

    def kills(self) -> List[FaultSpec]:
        """The real-signal specs, in firing order (FleetFaultDriver)."""
        return sorted((f for f in self.faults if f.kind == "kill"),
                      key=lambda f: f.at_s)


class FaultInjector:
    """Per-replica fault_hook driven by the scheduler's own ticks."""

    def __init__(self, faults: Sequence[FaultSpec]) -> None:
        self.faults = sorted(faults, key=lambda f: f.tick)
        self.tick = 0
        self.crashed_until: Optional[float] = None  # None = not crashed;
        #                                             inf = permanent
        self._admit_fails_pending = 0

    def alive(self, now: float) -> bool:
        """Probe answer: has the injected crash window passed?"""
        return self.crashed_until is None or now >= self.crashed_until

    def revive(self) -> None:
        """Called by the router when a probe finds the replica back up
        (the restarted process starts with a clean fault slate for its
        already-fired specs; future-tick specs still apply)."""
        self.crashed_until = None

    def on_tick(self, scheduler) -> None:
        """Top of Scheduler.step. Fires every spec scheduled for this
        tick; a crash raises after the cheaper faults are applied (they
        model pre-crash symptoms)."""
        self.tick += 1
        crash: Optional[FaultSpec] = None
        for f in self.faults:
            if f.tick != self.tick:
                continue
            if f.kind == "latency":
                self._stall(scheduler.clock, f.delay_s)
            elif f.kind == "nan_logits":
                scheduler.engine.poison_slot(f.slot)
            elif f.kind == "admit_fail":
                self._admit_fails_pending += 1
            elif f.kind == "crash":
                crash = f
        if crash is not None:
            now = scheduler.clock.now()
            self.crashed_until = (
                now + crash.down_s if crash.down_s > 0 else math.inf
            )
            raise ReplicaCrashed(
                f"injected crash at tick {self.tick} "
                f"(down_s={crash.down_s})"
            )

    def take_admit_fault(self) -> bool:
        """Consume one pending admission failure (Scheduler._admit).
        Armed faults persist until consumed (see module doc: sticky, so
        an empty queue at the planned tick defers rather than drops)."""
        if self._admit_fails_pending > 0:
            self._admit_fails_pending -= 1
            return True
        return False

    @staticmethod
    def _stall(clock, delay_s: float) -> None:
        advance = getattr(clock, "advance", None)
        if advance is not None:   # FakeClock: virtual stall, no real wait
            advance(delay_s)
        else:
            time.sleep(delay_s)


class FleetFaultDriver:
    """Fires a plan's ``kill`` specs at REAL worker processes.

    `kill_fn(replica, sig_name)` is injected (the supervisor's `kill`,
    which resolves the replica's CURRENT pid — a restarted worker has a
    new one) so the firing logic is host-pure testable. `poll(elapsed)`
    is called from the fleet's drive loop with seconds since the run
    started; each spec fires exactly once, at the first poll at or
    after its `at_s`. Misses are impossible by construction (a late
    poll still fires everything due), which keeps a kill plan as
    replayable as the simulated ones — modulo the OS scheduling the
    run is there to expose.
    """

    def __init__(self, plan: FaultPlan, kill_fn) -> None:
        self.pending: List[FaultSpec] = plan.kills()
        self.kill_fn = kill_fn
        self.fired: List[FaultSpec] = []

    def poll(self, elapsed_s: float) -> List[FaultSpec]:
        """Deliver every not-yet-fired kill with at_s <= elapsed_s;
        returns the specs fired by THIS poll."""
        fired_now: List[FaultSpec] = []
        while self.pending and self.pending[0].at_s <= elapsed_s:
            spec = self.pending.pop(0)
            self.kill_fn(spec.replica, spec.sig)
            fired_now.append(spec)
        self.fired.extend(fired_now)
        return fired_now

    @property
    def done(self) -> bool:
        return not self.pending
