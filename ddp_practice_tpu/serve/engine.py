"""Continuous-batching engine core: two jitted programs, zero recompiles.

The one-shot path (inference.make_generate_fn) compiles prefill + a
`lax.scan` of decode steps into ONE program per (batch, prompt_len,
max_new_tokens) triple — a new request shape means a new XLA program,
and nothing can join until the scan returns. This engine splits the
same `decode_apply` primitive into two separately-jitted functions with
STATIC shapes, so batch composition can churn at token granularity:

- `prefill+admit` (one compile per prompt bucket width): run the new
  request's prompt through a batch-1 scratch cache positioned to end at
  the pool cursor, then scatter the scratch rows + next-token logits
  into the pool at the slot index (kv_slots.write_slot);
- `decode step` (one compile, ever): sample one token per slot from the
  carried last-logits, apply the model batch-wide at s=1, return new
  logits/tokens. Free slots ride along emitting pad tokens — their rows
  are garbage by construction and invisible by masking. A lax.scan runs
  `decode_burst` such steps per dispatch (multi-step scheduling) so the
  constant host/dispatch cost amortizes over K tokens; releases become
  burst-granular, the tokens do not change (pinned in
  tests/test_serve_engine.py).

Prompts are LEFT-padded into a small set of bucket widths
(EngineConfig.prompt_buckets), so the prefill jit cache is bounded by
the bucket count however many distinct prompt lengths arrive — the
"no recompilation churn" property the scheduler tests pin via
`compile_stats()`.

Sampling is per-slot (each request carries its own fold_in'd PRNG
chain), so a request's tokens do not depend on what else shares the
batch — the property that makes continuous batching transparent to
clients. Greedy decode is bit-identical to the one-shot generator
(tests/test_serve_equivalence.py) because both paths run the same
`decode_apply` and the same `sample_logits`.

Two engines share this contract behind one interface (`admit_gate` /
`admit` / `step_burst` / `release` / `compile_stats`): SlotEngine over
the shared-cursor slot pool (kv_slots.py) and PagedEngine over the
block-granular paged pool (kv_pages.py — per-slot page tables, no
global clock, contexts past max_len). The scheduler drives either.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ddp_practice_tpu.inference import decode_apply, make_cache, sample_logits
from ddp_practice_tpu.serve.kv_pages import (
    BlockAllocator,
    make_paged_cache,
    scatter_prompt_blocks,
)
from ddp_practice_tpu.serve.kv_slots import (
    SlotAllocator,
    set_cursor,
    write_slot,
)
from ddp_practice_tpu.utils.trace import (
    ENGINE_LANE,
    NULL_SPAN as _NULL,
    SLOT_LANE_BASE,
)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Compile-time serving knobs (all closed over by the jitted fns)."""

    max_slots: int = 4
    # pool positions per slot; 0 = the model's max_len. For PagedEngine
    # this sizes the DEFAULTS of the block pool (num_blocks /
    # max_blocks_per_slot below), not a hard span — per-slot capacity is
    # max_blocks_per_slot * block_size and may exceed the model's
    # max_len (RoPE positions are unbounded).
    max_len: int = 0
    # LEFT-pad prompt widths for the bucketed prefill compile cache; the
    # largest bucket is also the base cursor (admission always has room
    # to place a full-width prompt behind the cursor)
    prompt_buckets: Tuple[int, ...] = (8, 16, 32, 64)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    eos_id: Optional[int] = None
    pad_id: int = 0
    # decode steps per dispatch (multi-step scheduling): a lax.scan of K
    # single-token steps amortizes the per-dispatch host overhead K-fold
    # at the cost of slot-release granularity — a request finishing
    # mid-burst holds its slot (and the scheduler discards its surplus
    # tokens) until the burst boundary, E[K/2] wasted slot-steps per
    # request vs the static baseline's E[max - asked]. K=1 is exact
    # token-granularity scheduling (the deterministic-test setting).
    decode_burst: int = 1
    # ---- PagedEngine knobs (ignored by SlotEngine) ----
    # positions per pool block; the allocation granule. Multiples of 8
    # keep the TPU kernel's sublane tiling happy (ops/decode_attention).
    block_size: int = 16
    # pool blocks; 0 = 1 garbage block + max_slots * max_blocks_per_slot
    # (full backing — every slot can reach its capacity simultaneously).
    # Set smaller to oversubscribe (admission then gates on blocks).
    num_blocks: int = 0
    # per-slot page-table width = context cap in blocks; 0 =
    # ceil(max_len / block_size). THIS is a slot's attention span — size
    # it to the workload's real contexts, not the pool.
    max_blocks_per_slot: int = 0


def _sample_step(cfg: EngineConfig, last_logits, active, keys):
    """One sampling step shared by both engines: per-slot PRNG chains,
    greedy fast path, pad tokens for free slots. Returns
    (tokens int32, new_keys)."""
    if cfg.temperature == 0.0:
        toks = sample_logits(last_logits, None, temperature=0.0)
        new_keys = keys
    else:
        split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
        subs, new_keys = split[:, 0], split[:, 1]
        toks = jax.vmap(
            lambda lg, k: sample_logits(
                lg[None], k, temperature=cfg.temperature,
                top_k=cfg.top_k, top_p=cfg.top_p,
            )[0]
        )(last_logits, subs)
    toks = jnp.where(
        active, toks.astype(jnp.int32), jnp.int32(cfg.pad_id)
    )
    return toks, new_keys


def _decode_donate() -> tuple:
    """donate_argnums for the decode dispatch: the cache pool (arg 1
    after params) is donated on TPU so XLA reuses its HBM in place —
    with a paged pool the buffer is the whole serving memory, big enough
    to care (ROADMAP engine-level item). Gated off on CPU, where
    donation is unimplemented and every dispatch would warn."""
    return (1,) if jax.default_backend() == "tpu" else ()


class _EngineBase:
    """What the two memory layouts share: the prompt-bucket map, slot
    accounting over a SlotAllocator at `self.allocator`, the
    token-granular `step()` veneer over `step_burst`, the
    two-jitted-programs observable (`self._prefill_jit` /
    `self._decode_jit` set by each subclass __init__), and the optional
    tracer (`set_tracer`): per-dispatch prefill / decode-burst lane
    spans plus `jax.profiler.TraceAnnotation` regions NAMED with the
    dispatch's trace-ids, so a device trace (utils/profiling.py ->
    utils/xprof.py) lines up with the host spans. tracer=None (default)
    keeps the dispatch path annotation-free."""

    # set by each subclass __init__ via set_tracer defaults
    tracer = None
    replica = 0

    def set_tracer(self, tracer, replica: int = 0) -> None:
        """Attach a utils/trace.py TraceRecorder; `replica` is this
        engine's pid in the exported timeline (lane conventions:
        trace.label_replica)."""
        self.tracer = tracer
        self.replica = replica

    def _dispatch_ids(self) -> list:
        """Active slots' trace-ids in slot order (decode annotation)."""
        return [self._slot_trace.get(s, f"slot{s}")
                for s in np.flatnonzero(self._active)]

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest bucket width holding `prompt_len` (raises if none)."""
        for w in self.buckets:
            if prompt_len <= w:
                return w
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )

    @property
    def num_active(self) -> int:
        return self.allocator.num_used

    @property
    def num_free(self) -> int:
        return self.allocator.num_free

    def step(self) -> np.ndarray:
        """One decode step for the whole pool; tokens (max_slots,).
        Token-granular stepping — requires decode_burst=1 (use
        step_burst for the amortized path)."""
        if self.config.decode_burst != 1:
            raise RuntimeError("step() needs decode_burst=1")
        return self.step_burst()[0]

    def compile_stats(self) -> dict:
        """Jit cache sizes — the no-recompilation-churn observable.

        After warmup (one admit per bucket width in play, one decode
        dispatch), these counts must stay CONSTANT however many requests
        churn through (pinned via the conftest `compile_guard` helper
        and tests/test_serve_scheduler.py)."""
        return {
            "prefill_compiles": self._prefill_jit._cache_size(),
            "decode_compiles": self._decode_jit._cache_size(),
        }


class SlotEngine(_EngineBase):
    """Slot-granular admission + batched single-token decode.

    Pure mechanism: WHAT to admit/release and WHEN is the scheduler's
    job (serve/scheduler.py); this class owns the device state (cache
    pool, last-logits, attention starts, per-slot PRNG keys) and the two
    jitted programs. All host<->device traffic per step is one token
    vector readback.
    """

    def __init__(self, model, params, config: EngineConfig = EngineConfig(),
                 *, batch_stats: Any = None) -> None:
        if getattr(model, "pos_emb", None) != "rope":
            raise ValueError(
                "SlotEngine needs pos_emb='rope' — slot admission "
                "left-aligns prompts at arbitrary cache offsets, which "
                "only relative positions survive (models/lm.py attn_start)"
            )
        if not config.prompt_buckets:
            raise ValueError("prompt_buckets must be non-empty")
        self.model = model
        self.params = params
        self.batch_stats = batch_stats
        self.config = config
        self.max_len = config.max_len or model.max_len
        self.buckets = tuple(sorted(set(config.prompt_buckets)))
        self.base_cursor = self.buckets[-1]
        if self.base_cursor >= self.max_len:
            raise ValueError(
                f"largest prompt bucket {self.base_cursor} leaves no decode "
                f"headroom in max_len {self.max_len}"
            )
        s = config.max_slots
        self.allocator = SlotAllocator(s)
        self.cursor = self.base_cursor  # host mirror of the device cursor
        self._cache = set_cursor(
            make_cache(model, s, self.max_len), self.base_cursor
        )
        self._last_logits = jnp.zeros((s, model.vocab_size), model.dtype)
        self._attn_starts = jnp.zeros((s,), jnp.int32)
        self._keys = jnp.zeros((s, 2), jnp.uint32)
        self._active = np.zeros((s,), bool)
        self.last_finite = np.ones((1, s), bool)  # updated per step_burst
        self._slot_trace: dict = {}  # slot -> trace_id (tracer attached)
        if config.decode_burst < 1:
            raise ValueError("decode_burst must be >= 1")
        self._prefill_jit = jax.jit(self._prefill_admit)
        self._decode_jit = jax.jit(
            self._decode_burst, donate_argnums=_decode_donate()
        )

    # ---------------------------------------------------------------- jitted
    def _prefill_admit(self, params, pool, last_logits, attn_starts,
                       tokens, start, attn_start, slot):
        """tokens (1, w) left-padded; start = cursor - w; one compile per w."""
        scratch = set_cursor(make_cache(self.model, 1, self.max_len), start)
        scratch, logits = decode_apply(
            self.model, params, scratch, tokens,
            attn_start=attn_start[None], batch_stats=self.batch_stats,
        )
        pool = write_slot(pool, scratch, slot)
        last_logits = lax.dynamic_update_slice(
            last_logits, logits[:, -1].astype(last_logits.dtype), (slot, 0)
        )
        attn_starts = lax.dynamic_update_slice(
            attn_starts, attn_start[None], (slot,)
        )
        return pool, last_logits, attn_starts

    def _decode_body(self, params, pool, last_logits, attn_starts,
                     active, keys):
        cfg = self.config
        # per-slot finite-logits flag, computed on the SAMPLING INPUT: a
        # non-finite row (bf16 overflow, poisoned cache) marks only its
        # own slot — attention is per-row, so the NaN cannot cross slots,
        # and this flag is what lets the scheduler finish ONE request
        # with status "error" instead of serving garbage batch-wide
        finite = jnp.isfinite(last_logits).all(axis=-1)
        toks, new_keys = _sample_step(cfg, last_logits, active, keys)
        pool, logits = decode_apply(
            self.model, params, pool, toks[:, None],
            attn_start=attn_starts, batch_stats=self.batch_stats,
        )
        return pool, logits[:, -1], toks, new_keys, finite

    def _decode_burst(self, params, pool, last_logits, attn_starts,
                      active, keys):
        """lax.scan of `decode_burst` single-token steps per dispatch —
        the host-overhead amortizer (multi-step scheduling). Returns
        tokens (K, max_slots); K=1 is plain token-granular stepping."""

        def body(carry, _):
            pool, last_logits, keys = carry
            pool, last_logits, toks, keys, finite = self._decode_body(
                params, pool, last_logits, attn_starts, active, keys
            )
            return (pool, last_logits, keys), (toks, finite)

        (pool, last_logits, keys), (toks, finite) = lax.scan(
            body, (pool, last_logits, keys), None,
            length=self.config.decode_burst,
        )
        return pool, last_logits, toks, keys, finite

    # ----------------------------------------------------------------- host
    @property
    def headroom(self) -> int:
        """Decode positions left before the pool cursor hits max_len."""
        return self.max_len - self.cursor

    def admit_gate(self, prompt_len: int, needed_positions: int) -> str:
        """Admission verdict for a request needing `needed_positions`
        decode positions (burst-rounded by the scheduler):
        "ok" = admit now; "later" = cannot yet (positions will free —
        here, after a drain + `make_room` rewind); "never" = can never
        run on this engine (prompt outgrows every bucket, or more
        positions than a fresh pool holds)."""
        try:
            self.bucket_for(prompt_len)
        except ValueError:
            return "never"
        if needed_positions > self.max_len - self.base_cursor:
            return "never"
        if self.headroom < needed_positions:
            return "later"
        return "ok"

    def make_room(self) -> bool:
        """Try to create admission headroom; True if anything changed.
        Positions are a global resource under the shared cursor — the
        only lever is rewinding the pool clock once every slot is free
        (the scheduler drains, then calls this). The paged engine has no
        equivalent: its blocks free individually at release."""
        if self.allocator.num_used == 0 and self.cursor != self.base_cursor:
            self.reset_epoch()
            return True
        return False

    def admit(self, prompt: Sequence[int], *, seed: int = 0,
              max_positions: Optional[int] = None,
              trace_id: Optional[str] = None) -> int:
        """Prefill `prompt` into a free slot; returns the slot index.

        The prompt joins exactly where the running batch is: its last
        token's K/V lands at `cursor - 1`, so the NEXT decode step
        produces its first generated token together with everyone
        else's. Raises if no slot is free or the prompt outgrows the
        buckets — admission POLICY (queueing, shedding) lives in the
        scheduler. `max_positions` is accepted for engine-interface
        parity with PagedEngine (which reserves blocks per request) and
        ignored here: slot-pool positions are a global resource.
        `trace_id` names the prefill span / profiler annotation when a
        tracer is attached.
        """
        p = len(prompt)
        if p == 0:
            raise ValueError("prompt must contain at least one token")
        w = self.bucket_for(p)
        slot = self.allocator.alloc()
        if slot is None:
            raise RuntimeError("no free slot — scheduler must gate admits")
        start = self.cursor - w
        assert start >= 0, (self.cursor, w)  # cursor >= base >= every bucket
        padded = np.full((1, w), self.config.pad_id, np.int32)
        padded[0, w - p:] = np.asarray(prompt, np.int32)
        tr = self.tracer
        if tr is not None and tr.enabled:
            tid = trace_id or f"slot{slot}"
            self._slot_trace[slot] = tid
            span = tr.span("prefill", trace_id=tid, pid=self.replica,
                           tid=SLOT_LANE_BASE + slot, bucket=w,
                           prompt_len=p, slot=slot)
            ann = jax.profiler.TraceAnnotation(f"serve:prefill:{tid}")
        else:
            span = ann = _NULL
        with span, ann:
            (self._cache, self._last_logits,
             self._attn_starts) = self._prefill_jit(
                self.params, self._cache, self._last_logits,
                self._attn_starts,
                jnp.asarray(padded), jnp.int32(start),
                jnp.int32(self.cursor - p), jnp.int32(slot),
            )
        # keyed by the REQUEST's seed alone (not the slot), so a
        # request's sampled tokens are independent of where admission
        # happened to place it — batch composition stays invisible
        self._keys = self._keys.at[slot].set(jax.random.PRNGKey(seed))
        self._active[slot] = True
        return slot

    def step_burst(self) -> np.ndarray:
        """One dispatch of `decode_burst` steps; tokens (K, max_slots).

        Advances the shared cursor by K positions. Entries for free
        slots are pad_id; the scheduler maps active slots' token rows
        back to their requests, decides EOS/length/deadline release,
        and discards rows past a request's release point.
        """
        k = self.config.decode_burst
        if self.headroom < k:
            raise RuntimeError(
                "pool positions exhausted — drain and reset_epoch()"
            )
        tr = self.tracer
        if tr is not None and tr.enabled:
            ids = self._dispatch_ids()
            span = tr.span("decode_burst", pid=self.replica,
                           tid=ENGINE_LANE, burst=k, active=len(ids),
                           cursor=self.cursor)
            ann = jax.profiler.TraceAnnotation(
                "serve:decode[" + ",".join(ids) + "]"
            )
        else:
            span = ann = _NULL
        with span, ann:
            (self._cache, self._last_logits, toks,
             self._keys, finite) = self._decode_jit(
                self.params, self._cache, self._last_logits,
                self._attn_starts,
                jnp.asarray(self._active), self._keys,
            )
            self.cursor += k
            toks, finite = jax.device_get((toks, finite))
        # (K, max_slots) bool: False rows mark slots whose token this
        # burst was sampled from non-finite logits — the scheduler
        # finishes those requests with status "error"
        self.last_finite = np.asarray(finite)
        return np.asarray(toks)

    def poison_slot(self, slot: int) -> None:
        """Overwrite one slot's pending sampling input with NaN — the
        deterministic stand-in for a numerical blow-up (serve/faults.py
        `nan_logits`). Host-side, between dispatches; the next decode
        burst's finite flag turns False for exactly this slot."""
        self._last_logits = self._last_logits.at[slot].set(jnp.nan)

    def release(self, slot: int) -> None:
        """Free a slot. Pure bookkeeping: the next admission overwrites
        the slot's entire cache row (kv_slots.write_slot), so no device
        work happens at release time."""
        self.allocator.free(slot)
        self._active[slot] = False
        self._slot_trace.pop(slot, None)

    def reset_epoch(self) -> None:
        """Rewind the shared cursor to the base (all slots must be free).

        Positions are a global resource under the shared-cursor design;
        when the scheduler has drained all active requests it rewinds
        the clock instead of reallocating the pool. Stale K/V stays in
        the buffers — every future admission wipes its whole slot row.
        """
        if self.allocator.num_used:
            raise RuntimeError("reset_epoch with active slots")
        self._cache = set_cursor(self._cache, self.base_cursor)
        self._attn_starts = jnp.zeros_like(self._attn_starts)
        self.cursor = self.base_cursor


class PagedEngine(_EngineBase):
    """Paged-KV continuous batching: per-slot page tables, no shared clock.

    Same two-jitted-programs contract and public surface as SlotEngine
    (the scheduler drives either through `admit_gate` / `admit` /
    `step_burst` / `release`), but the cache is a pool of fixed-size
    blocks (serve/kv_pages.py) and every slot decodes at its OWN
    slot-local write position:

    - `admit` prefills the bucketed prompt into a batch-1 contiguous
      scratch cache at positions [0, w) and scatters it into freshly
      allocated blocks (one compile per bucket width, as before);
    - `step_burst` appends each active slot's token at `lengths[slot]`
      through the page table and attends only that slot's occupied
      pages (ops/decode_attention.paged_decode_attention) — a step's
      attention span is the request's own context, not a pool-global
      [0, max_len);
    - `release` returns the slot's blocks to the free list individually;
      nothing ever drains and nothing rewinds (no reset_epoch here);
    - a request may decode past the model's / slot engine's max_len:
      per-slot capacity is `max_blocks_per_slot * block_size` and RoPE
      positions are unbounded.

    Block accounting is LAZY with a worst-case reservation: admission
    reserves `ceil((bucket + max_positions) / block_size)` blocks (so a
    running request can never starve mid-decode — the deadlock-freedom
    the slot engine got from headroom gating), allocates only the prompt
    blocks up front, and draws the rest from its reservation at burst
    granularity as the context actually grows.
    """

    def __init__(self, model, params, config: EngineConfig = EngineConfig(),
                 *, batch_stats: Any = None) -> None:
        if getattr(model, "pos_emb", None) != "rope":
            raise ValueError(
                "PagedEngine needs pos_emb='rope' — slots decode at "
                "slot-local positions, which only relative positions "
                "survive (models/lm.py)"
            )
        if not config.prompt_buckets:
            raise ValueError("prompt_buckets must be non-empty")
        if config.decode_burst < 1:
            raise ValueError("decode_burst must be >= 1")
        if config.block_size < 1:
            raise ValueError("block_size must be positive")
        self.model = model
        self.params = params
        self.batch_stats = batch_stats
        self.config = config
        self.max_len = config.max_len or model.max_len
        self.buckets = tuple(sorted(set(config.prompt_buckets)))
        bs = config.block_size
        self.max_blocks_per_slot = (
            config.max_blocks_per_slot or -(-self.max_len // bs)
        )
        self.max_context = self.max_blocks_per_slot * bs
        if self.buckets[-1] > min(self.max_context - 1, model.max_len):
            raise ValueError(
                f"largest prompt bucket {self.buckets[-1]} must fit the "
                f"scratch prefill (model max_len {model.max_len}) and "
                f"leave decode room in the per-slot capacity "
                f"{self.max_context}"
            )
        s = config.max_slots
        num_blocks = (
            config.num_blocks or 1 + s * self.max_blocks_per_slot
        )
        self.allocator = SlotAllocator(s)     # slot ids (metrics reads it)
        self.blocks = BlockAllocator(num_blocks)
        self._cache = make_paged_cache(model, num_blocks, bs)
        self._last_logits = jnp.zeros((s, model.vocab_size), model.dtype)
        self._keys = jnp.zeros((s, 2), jnp.uint32)
        self._active = np.zeros((s,), bool)
        # host-side per-slot state; tiny, shipped to device per dispatch
        self._pt = np.zeros((s, self.max_blocks_per_slot), np.int32)
        self._len = np.zeros((s,), np.int32)
        self._attn = np.zeros((s,), np.int32)
        self._nblk = np.zeros((s,), np.int64)   # blocks allocated
        self._resv = np.zeros((s,), np.int64)   # blocks still reserved
        self.last_finite = np.ones((1, s), bool)
        self._slot_trace: dict = {}  # slot -> trace_id (tracer attached)
        self._prefill_jit = jax.jit(self._prefill_admit)
        self._decode_jit = jax.jit(
            self._decode_burst, donate_argnums=_decode_donate()
        )

    # ---------------------------------------------------------------- jitted
    def _prefill_admit(self, params, pool, last_logits, tokens,
                       attn_start, block_ids, slot):
        """tokens (1, w) left-padded; one compile per bucket width w.

        The scratch cache starts at cursor 0 — slot-local coordinates —
        so admission is placement-free: no alignment to anyone else's
        cursor, just a scatter of the w prefilled rows into this slot's
        blocks."""
        w = tokens.shape[1]
        scratch = make_cache(self.model, 1, w)
        scratch, logits = decode_apply(
            self.model, params, scratch, tokens,
            attn_start=attn_start[None], batch_stats=self.batch_stats,
        )
        pool = scatter_prompt_blocks(
            pool, scratch, block_ids, w, self.config.block_size
        )
        last_logits = lax.dynamic_update_slice(
            last_logits, logits[:, -1].astype(last_logits.dtype), (slot, 0)
        )
        return pool, last_logits

    def _decode_burst(self, params, pool, last_logits, attn_starts,
                      active, keys, page_table, lengths):
        """lax.scan of `decode_burst` paged single-token steps. Each step
        writes active slots' K/V at their own `lengths` position and
        advances only active lengths; retired slots keep scattering into
        the garbage block (kv_pages.GARBAGE_BLOCK) so shapes stay static."""

        def body(carry, _):
            pool, last_logits, keys, lengths = carry
            finite = jnp.isfinite(last_logits).all(axis=-1)
            toks, keys = _sample_step(self.config, last_logits, active, keys)
            pool, logits = decode_apply(
                self.model, params, pool, toks[:, None],
                attn_start=attn_starts, batch_stats=self.batch_stats,
                page_table=page_table, kv_lengths=lengths,
            )
            lengths = lengths + active.astype(lengths.dtype)
            return (pool, logits[:, -1], keys, lengths), (toks, finite)

        (pool, last_logits, keys, _), (toks, finite) = lax.scan(
            body, (pool, last_logits, keys, lengths), None,
            length=self.config.decode_burst,
        )
        return pool, last_logits, toks, keys, finite

    # ----------------------------------------------------------------- host
    def _blocks_for(self, positions: int) -> int:
        return -(-positions // self.config.block_size)

    @property
    def blocks_available(self) -> int:
        """Free blocks not spoken for by running requests' reservations —
        what admission can actually promise to a new request."""
        return self.blocks.num_free - int(self._resv.sum())

    @property
    def headroom(self) -> int:
        """Unreserved pool positions (informational — admission gates on
        blocks per request, not on a global span)."""
        return self.blocks_available * self.config.block_size

    def admit_gate(self, prompt_len: int, needed_positions: int) -> str:
        """"ok" | "later" (blocks free as running requests release) |
        "never" (outgrows every bucket or the per-slot capacity)."""
        try:
            w = self.bucket_for(prompt_len)
        except ValueError:
            return "never"
        if w + needed_positions > self.max_context:
            return "never"
        worst = self._blocks_for(w + needed_positions)
        if worst > self.blocks.num_blocks - 1:
            return "never"  # outgrows the whole pool, even empty
        if worst > self.blocks_available:
            return "later"
        return "ok"

    def make_room(self) -> bool:
        """Nothing to do: pages free individually at release — there is
        no epoch to rewind (the scheduler's drain path never triggers)."""
        return False

    def admit(self, prompt: Sequence[int], *, seed: int = 0,
              max_positions: Optional[int] = None,
              trace_id: Optional[str] = None) -> int:
        """Prefill `prompt` into a free slot + fresh blocks; the slot id.

        `max_positions` is the request's decode-position budget
        (burst-rounded max_new_tokens from the scheduler) — it sizes the
        block reservation that guarantees the request can always finish.
        None reserves up to the per-slot capacity (direct engine users:
        fine for tests, wasteful under concurrency).
        """
        p = len(prompt)
        if p == 0:
            raise ValueError("prompt must contain at least one token")
        w = self.bucket_for(p)
        if max_positions is None:
            max_positions = self.max_context - w
        if w + max_positions > self.max_context:
            raise ValueError(
                f"prompt bucket {w} + max_positions {max_positions} "
                f"exceeds the per-slot capacity {self.max_context} "
                f"(= max_blocks_per_slot * block_size)"
            )
        worst = self._blocks_for(w + max_positions)
        if worst > self.blocks_available:
            raise RuntimeError(
                "not enough free blocks — scheduler must gate admits"
            )
        slot = self.allocator.alloc()
        if slot is None:
            raise RuntimeError("no free slot — scheduler must gate admits")
        n_prompt = self._blocks_for(w)
        ids = self.blocks.alloc(n_prompt)
        assert ids is not None  # worst >= n_prompt <= blocks_available
        self._pt[slot, :] = 0
        self._pt[slot, :n_prompt] = ids
        self._nblk[slot] = n_prompt
        self._resv[slot] = worst - n_prompt
        self._len[slot] = w
        self._attn[slot] = w - p
        padded = np.full((1, w), self.config.pad_id, np.int32)
        padded[0, w - p:] = np.asarray(prompt, np.int32)
        tr = self.tracer
        if tr is not None and tr.enabled:
            tid = trace_id or f"slot{slot}"
            self._slot_trace[slot] = tid
            span = tr.span("prefill", trace_id=tid, pid=self.replica,
                           tid=SLOT_LANE_BASE + slot, bucket=w,
                           prompt_len=p, slot=slot, blocks=n_prompt)
            ann = jax.profiler.TraceAnnotation(f"serve:prefill:{tid}")
        else:
            span = ann = _NULL
        with span, ann:
            self._cache, self._last_logits = self._prefill_jit(
                self.params, self._cache, self._last_logits,
                jnp.asarray(padded), jnp.int32(w - p),
                jnp.asarray(ids, jnp.int32), jnp.int32(slot),
            )
        # keyed by the REQUEST's seed alone, as in SlotEngine: placement
        # must stay invisible to the sample stream
        self._keys = self._keys.at[slot].set(jax.random.PRNGKey(seed))
        self._active[slot] = True
        return slot

    def _grow_tables(self, k: int) -> int:
        """Allocate the blocks the next k decode positions need, per
        active slot, drawing from each slot's reservation (so allocation
        cannot fail mid-decode — exhaustion was settled at admission).
        Stepping a slot past what its admission reserved raises BEFORE
        touching the allocator (the analogue of SlotEngine's
        positions-exhausted guard; the scheduler's burst-rounded
        max_positions never trips it). Returns the number of blocks
        grown (the decode-burst span's `blocks_grown` attribute)."""
        total_grown = 0
        for slot in np.flatnonzero(self._active):
            need = self._blocks_for(int(self._len[slot]) + k)
            grow = need - int(self._nblk[slot])
            if grow <= 0:
                continue
            if grow > int(self._resv[slot]) or need > self.max_blocks_per_slot:
                raise RuntimeError(
                    f"slot {slot} stepped past its admit-time block "
                    f"reservation (needs {need} blocks, has "
                    f"{int(self._nblk[slot])} + {int(self._resv[slot])} "
                    f"reserved) — admit with a larger max_positions"
                )
            ids = self.blocks.alloc(grow)
            # cannot fail: sum(_resv) <= blocks.num_free is the admission
            # invariant, and grow <= _resv[slot] was just checked
            assert ids is not None, "reservation accounting broke"
            self._pt[slot, self._nblk[slot]:need] = ids
            self._nblk[slot] = need
            self._resv[slot] -= grow
            total_grown += grow
        return total_grown

    def step_burst(self) -> np.ndarray:
        """One dispatch of `decode_burst` steps; tokens (K, max_slots).
        Per-slot lengths advance by K for active slots; free slots emit
        pad_id and write only the garbage block."""
        k = self.config.decode_burst
        grown = self._grow_tables(k)
        tr = self.tracer
        if tr is not None and tr.enabled:
            ids = self._dispatch_ids()
            span = tr.span("decode_burst", pid=self.replica,
                           tid=ENGINE_LANE, burst=k, active=len(ids),
                           blocks_grown=grown,
                           blocks_free=self.blocks.num_free)
            ann = jax.profiler.TraceAnnotation(
                "serve:decode[" + ",".join(ids) + "]"
            )
        else:
            span = ann = _NULL
        with span, ann:
            (self._cache, self._last_logits, toks,
             self._keys, finite) = self._decode_jit(
                self.params, self._cache, self._last_logits,
                jnp.asarray(self._attn), jnp.asarray(self._active),
                self._keys, jnp.asarray(self._pt), jnp.asarray(self._len),
            )
            self._len[self._active] += k
            toks, finite = jax.device_get((toks, finite))
        self.last_finite = np.asarray(finite)
        return np.asarray(toks)

    def context_len(self, slot: int) -> int:
        """The slot's current context length (bucket width + decoded
        tokens) — can exceed the model's max_len, the paged headline."""
        return int(self._len[slot])

    def poison_slot(self, slot: int) -> None:
        """NaN one slot's pending sampling input (serve/faults.py) —
        identical contract to SlotEngine.poison_slot."""
        self._last_logits = self._last_logits.at[slot].set(jnp.nan)

    def release(self, slot: int) -> None:
        """Free the slot and return its blocks to the pool individually.
        The page-table row is pointed back at the garbage block so the
        batched decode keeps static shapes; stale K/V in the freed
        blocks is invisible to the next occupant (masked to its own
        written positions — pinned in tests/test_kv_pages.py)."""
        n = int(self._nblk[slot])
        if n:
            self.blocks.free([int(b) for b in self._pt[slot, :n]])
        self.allocator.free(slot)
        self._pt[slot, :] = 0
        self._nblk[slot] = 0
        self._resv[slot] = 0
        self._len[slot] = 0
        self._attn[slot] = 0
        self._active[slot] = False
        self._slot_trace.pop(slot, None)

    def reset_epoch(self) -> None:
        """Interface parity with SlotEngine (the router calls this in
        warmup() and replica restart()): there is no pool clock to
        rewind — every release already returned its pages — so with all
        slots free this is a no-op; with active slots it raises, same
        contract as the slot pool."""
        if self.allocator.num_used:
            raise RuntimeError("reset_epoch with active slots")
