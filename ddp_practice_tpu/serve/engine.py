"""Continuous-batching engine core: two jitted programs, zero recompiles.

The one-shot path (inference.make_generate_fn) compiles prefill + a
`lax.scan` of decode steps into ONE program per (batch, prompt_len,
max_new_tokens) triple — a new request shape means a new XLA program,
and nothing can join until the scan returns. This engine splits the
same `decode_apply` primitive into two separately-jitted functions with
STATIC shapes, so batch composition can churn at token granularity:

- `prefill+admit` (one compile per prompt bucket width): run the new
  request's prompt through a batch-1 scratch cache positioned to end at
  the pool cursor, then scatter the scratch rows + next-token logits
  into the pool at the slot index (kv_slots.write_slot);
- `decode step` (one compile, ever): sample one token per slot from the
  carried last-logits, apply the model batch-wide at s=1, return new
  logits/tokens. Free slots ride along emitting pad tokens — their rows
  are garbage by construction and invisible by masking. A lax.scan runs
  `decode_burst` such steps per dispatch (multi-step scheduling) so the
  constant host/dispatch cost amortizes over K tokens; releases become
  burst-granular, the tokens do not change (pinned in
  tests/test_serve_engine.py).

Prompts are LEFT-padded into a small set of bucket widths
(EngineConfig.prompt_buckets), so the prefill jit cache is bounded by
the bucket count however many distinct prompt lengths arrive — the
"no recompilation churn" property the scheduler tests pin via
`compile_stats()`.

Sampling is per-slot (each request carries its own fold_in'd PRNG
chain), so a request's tokens do not depend on what else shares the
batch — the property that makes continuous batching transparent to
clients. Greedy decode is bit-identical to the one-shot generator
(tests/test_serve_equivalence.py) because both paths run the same
`decode_apply` and the same `sample_logits`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ddp_practice_tpu.inference import decode_apply, make_cache, sample_logits
from ddp_practice_tpu.serve.kv_slots import (
    SlotAllocator,
    set_cursor,
    write_slot,
)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Compile-time serving knobs (all closed over by the jitted fns)."""

    max_slots: int = 4
    # pool positions per slot; 0 = the model's max_len
    max_len: int = 0
    # LEFT-pad prompt widths for the bucketed prefill compile cache; the
    # largest bucket is also the base cursor (admission always has room
    # to place a full-width prompt behind the cursor)
    prompt_buckets: Tuple[int, ...] = (8, 16, 32, 64)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    eos_id: Optional[int] = None
    pad_id: int = 0
    # decode steps per dispatch (multi-step scheduling): a lax.scan of K
    # single-token steps amortizes the per-dispatch host overhead K-fold
    # at the cost of slot-release granularity — a request finishing
    # mid-burst holds its slot (and the scheduler discards its surplus
    # tokens) until the burst boundary, E[K/2] wasted slot-steps per
    # request vs the static baseline's E[max - asked]. K=1 is exact
    # token-granularity scheduling (the deterministic-test setting).
    decode_burst: int = 1


class SlotEngine:
    """Slot-granular admission + batched single-token decode.

    Pure mechanism: WHAT to admit/release and WHEN is the scheduler's
    job (serve/scheduler.py); this class owns the device state (cache
    pool, last-logits, attention starts, per-slot PRNG keys) and the two
    jitted programs. All host<->device traffic per step is one token
    vector readback.
    """

    def __init__(self, model, params, config: EngineConfig = EngineConfig(),
                 *, batch_stats: Any = None) -> None:
        if getattr(model, "pos_emb", None) != "rope":
            raise ValueError(
                "SlotEngine needs pos_emb='rope' — slot admission "
                "left-aligns prompts at arbitrary cache offsets, which "
                "only relative positions survive (models/lm.py attn_start)"
            )
        if not config.prompt_buckets:
            raise ValueError("prompt_buckets must be non-empty")
        self.model = model
        self.params = params
        self.batch_stats = batch_stats
        self.config = config
        self.max_len = config.max_len or model.max_len
        self.buckets = tuple(sorted(set(config.prompt_buckets)))
        self.base_cursor = self.buckets[-1]
        if self.base_cursor >= self.max_len:
            raise ValueError(
                f"largest prompt bucket {self.base_cursor} leaves no decode "
                f"headroom in max_len {self.max_len}"
            )
        s = config.max_slots
        self.allocator = SlotAllocator(s)
        self.cursor = self.base_cursor  # host mirror of the device cursor
        self._cache = set_cursor(
            make_cache(model, s, self.max_len), self.base_cursor
        )
        self._last_logits = jnp.zeros((s, model.vocab_size), model.dtype)
        self._attn_starts = jnp.zeros((s,), jnp.int32)
        self._keys = jnp.zeros((s, 2), jnp.uint32)
        self._active = np.zeros((s,), bool)
        self.last_finite = np.ones((1, s), bool)  # updated per step_burst
        if config.decode_burst < 1:
            raise ValueError("decode_burst must be >= 1")
        self._prefill_jit = jax.jit(self._prefill_admit)
        self._decode_jit = jax.jit(self._decode_burst)

    # ---------------------------------------------------------------- jitted
    def _prefill_admit(self, params, pool, last_logits, attn_starts,
                       tokens, start, attn_start, slot):
        """tokens (1, w) left-padded; start = cursor - w; one compile per w."""
        scratch = set_cursor(make_cache(self.model, 1, self.max_len), start)
        scratch, logits = decode_apply(
            self.model, params, scratch, tokens,
            attn_start=attn_start[None], batch_stats=self.batch_stats,
        )
        pool = write_slot(pool, scratch, slot)
        last_logits = lax.dynamic_update_slice(
            last_logits, logits[:, -1].astype(last_logits.dtype), (slot, 0)
        )
        attn_starts = lax.dynamic_update_slice(
            attn_starts, attn_start[None], (slot,)
        )
        return pool, last_logits, attn_starts

    def _decode_body(self, params, pool, last_logits, attn_starts,
                     active, keys):
        cfg = self.config
        # per-slot finite-logits flag, computed on the SAMPLING INPUT: a
        # non-finite row (bf16 overflow, poisoned cache) marks only its
        # own slot — attention is per-row, so the NaN cannot cross slots,
        # and this flag is what lets the scheduler finish ONE request
        # with status "error" instead of serving garbage batch-wide
        finite = jnp.isfinite(last_logits).all(axis=-1)
        if cfg.temperature == 0.0:
            toks = sample_logits(last_logits, None, temperature=0.0)
            new_keys = keys
        else:
            split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
            subs, new_keys = split[:, 0], split[:, 1]
            toks = jax.vmap(
                lambda lg, k: sample_logits(
                    lg[None], k, temperature=cfg.temperature,
                    top_k=cfg.top_k, top_p=cfg.top_p,
                )[0]
            )(last_logits, subs)
        toks = jnp.where(
            active, toks.astype(jnp.int32), jnp.int32(cfg.pad_id)
        )
        pool, logits = decode_apply(
            self.model, params, pool, toks[:, None],
            attn_start=attn_starts, batch_stats=self.batch_stats,
        )
        return pool, logits[:, -1], toks, new_keys, finite

    def _decode_burst(self, params, pool, last_logits, attn_starts,
                      active, keys):
        """lax.scan of `decode_burst` single-token steps per dispatch —
        the host-overhead amortizer (multi-step scheduling). Returns
        tokens (K, max_slots); K=1 is plain token-granular stepping."""

        def body(carry, _):
            pool, last_logits, keys = carry
            pool, last_logits, toks, keys, finite = self._decode_body(
                params, pool, last_logits, attn_starts, active, keys
            )
            return (pool, last_logits, keys), (toks, finite)

        (pool, last_logits, keys), (toks, finite) = lax.scan(
            body, (pool, last_logits, keys), None,
            length=self.config.decode_burst,
        )
        return pool, last_logits, toks, keys, finite

    # ----------------------------------------------------------------- host
    def bucket_for(self, prompt_len: int) -> int:
        """Smallest bucket width holding `prompt_len` (raises if none)."""
        for w in self.buckets:
            if prompt_len <= w:
                return w
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )

    @property
    def headroom(self) -> int:
        """Decode positions left before the pool cursor hits max_len."""
        return self.max_len - self.cursor

    @property
    def num_active(self) -> int:
        return self.allocator.num_used

    @property
    def num_free(self) -> int:
        return self.allocator.num_free

    def admit(self, prompt: Sequence[int], *, seed: int = 0) -> int:
        """Prefill `prompt` into a free slot; returns the slot index.

        The prompt joins exactly where the running batch is: its last
        token's K/V lands at `cursor - 1`, so the NEXT decode step
        produces its first generated token together with everyone
        else's. Raises if no slot is free or the prompt outgrows the
        buckets — admission POLICY (queueing, shedding) lives in the
        scheduler.
        """
        p = len(prompt)
        if p == 0:
            raise ValueError("prompt must contain at least one token")
        w = self.bucket_for(p)
        slot = self.allocator.alloc()
        if slot is None:
            raise RuntimeError("no free slot — scheduler must gate admits")
        start = self.cursor - w
        assert start >= 0, (self.cursor, w)  # cursor >= base >= every bucket
        padded = np.full((1, w), self.config.pad_id, np.int32)
        padded[0, w - p:] = np.asarray(prompt, np.int32)
        (self._cache, self._last_logits,
         self._attn_starts) = self._prefill_jit(
            self.params, self._cache, self._last_logits, self._attn_starts,
            jnp.asarray(padded), jnp.int32(start),
            jnp.int32(self.cursor - p), jnp.int32(slot),
        )
        # keyed by the REQUEST's seed alone (not the slot), so a
        # request's sampled tokens are independent of where admission
        # happened to place it — batch composition stays invisible
        self._keys = self._keys.at[slot].set(jax.random.PRNGKey(seed))
        self._active[slot] = True
        return slot

    def step_burst(self) -> np.ndarray:
        """One dispatch of `decode_burst` steps; tokens (K, max_slots).

        Advances the shared cursor by K positions. Entries for free
        slots are pad_id; the scheduler maps active slots' token rows
        back to their requests, decides EOS/length/deadline release,
        and discards rows past a request's release point.
        """
        k = self.config.decode_burst
        if self.headroom < k:
            raise RuntimeError(
                "pool positions exhausted — drain and reset_epoch()"
            )
        (self._cache, self._last_logits, toks,
         self._keys, finite) = self._decode_jit(
            self.params, self._cache, self._last_logits, self._attn_starts,
            jnp.asarray(self._active), self._keys,
        )
        self.cursor += k
        toks, finite = jax.device_get((toks, finite))
        # (K, max_slots) bool: False rows mark slots whose token this
        # burst was sampled from non-finite logits — the scheduler
        # finishes those requests with status "error"
        self.last_finite = np.asarray(finite)
        return np.asarray(toks)

    def step(self) -> np.ndarray:
        """One decode step for the whole pool; tokens (max_slots,).
        Token-granular stepping — requires decode_burst=1 (use
        step_burst for the amortized path)."""
        if self.config.decode_burst != 1:
            raise RuntimeError("step() needs decode_burst=1")
        return self.step_burst()[0]

    def poison_slot(self, slot: int) -> None:
        """Overwrite one slot's pending sampling input with NaN — the
        deterministic stand-in for a numerical blow-up (serve/faults.py
        `nan_logits`). Host-side, between dispatches; the next decode
        burst's finite flag turns False for exactly this slot."""
        self._last_logits = self._last_logits.at[slot].set(jnp.nan)

    def release(self, slot: int) -> None:
        """Free a slot. Pure bookkeeping: the next admission overwrites
        the slot's entire cache row (kv_slots.write_slot), so no device
        work happens at release time."""
        self.allocator.free(slot)
        self._active[slot] = False

    def reset_epoch(self) -> None:
        """Rewind the shared cursor to the base (all slots must be free).

        Positions are a global resource under the shared-cursor design;
        when the scheduler has drained all active requests it rewinds
        the clock instead of reallocating the pool. Stale K/V stays in
        the buffers — every future admission wipes its whole slot row.
        """
        if self.allocator.num_used:
            raise RuntimeError("reset_epoch with active slots")
        self._cache = set_cursor(self._cache, self.base_cursor)
        self._attn_starts = jnp.zeros_like(self._attn_starts)
        self.cursor = self.base_cursor

    def compile_stats(self) -> dict:
        """Jit cache sizes — the no-recompilation-churn observable.

        After warmup (one admit per bucket width in play, one decode
        step), these counts must stay CONSTANT however many requests
        churn through (tests/test_serve_scheduler.py pins this).
        """
        return {
            "prefill_compiles": self._prefill_jit._cache_size(),
            "decode_compiles": self._decode_jit._cache_size(),
        }
