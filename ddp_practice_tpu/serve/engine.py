"""Continuous-batching engine core: two jitted programs, zero recompiles.

The one-shot path (inference.make_generate_fn) compiles prefill + a
`lax.scan` of decode steps into ONE program per (batch, prompt_len,
max_new_tokens) triple — a new request shape means a new XLA program,
and nothing can join until the scan returns. This engine splits the
same `decode_apply` primitive into two separately-jitted functions with
STATIC shapes, so batch composition can churn at token granularity:

- `prefill+admit` (one compile per prompt bucket width): run the new
  request's prompt through a batch-1 scratch cache positioned to end at
  the pool cursor, then scatter the scratch rows + next-token logits
  into the pool at the slot index (kv_slots.write_slot);
- `decode step` (one compile, ever): sample one token per slot from the
  carried last-logits, apply the model batch-wide at s=1, return new
  logits/tokens. Free slots ride along emitting pad tokens — their rows
  are garbage by construction and invisible by masking. A lax.scan runs
  `decode_burst` such steps per dispatch (multi-step scheduling) so the
  constant host/dispatch cost amortizes over K tokens; releases become
  burst-granular, the tokens do not change (pinned in
  tests/test_serve_engine.py).

Prompts are LEFT-padded into a small set of bucket widths
(EngineConfig.prompt_buckets), so the prefill jit cache is bounded by
the bucket count however many distinct prompt lengths arrive — the
"no recompilation churn" property the scheduler tests pin via
`compile_stats()`.

Sampling is per-slot (each request carries its own fold_in'd PRNG
chain), so a request's tokens do not depend on what else shares the
batch — the property that makes continuous batching transparent to
clients. Greedy decode is bit-identical to the one-shot generator
(tests/test_serve_equivalence.py) because both paths run the same
`decode_apply` and the same `sample_logits`.

Two engines share this contract behind one interface (`admit_gate` /
`admit` / `step_burst` / `release` / `compile_stats`): SlotEngine over
the shared-cursor slot pool (kv_slots.py) and PagedEngine over the
block-granular paged pool (kv_pages.py — per-slot page tables, no
global clock, contexts past max_len). The scheduler drives either.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ddp_practice_tpu.inference import (
    decode_apply,
    make_cache,
    sample_logits,
    sample_logits_batch,
)
from ddp_practice_tpu.serve.kv_pages import (
    GARBAGE_BLOCK,
    BlockAllocator,
    RadixPrefixCache,
    copy_block,
    make_paged_cache,
    rewind_block_tail,
    scatter_prompt_blocks,
)
from ddp_practice_tpu.serve.spec import DraftSource, PromptLookupDraft
from ddp_practice_tpu.serve.kv_slots import (
    SlotAllocator,
    set_cursor,
    write_slot,
)
from ddp_practice_tpu.utils.trace import (
    ENGINE_LANE,
    NULL_SPAN as _NULL,
    SLOT_LANE_BASE,
)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Compile-time serving knobs (all closed over by the jitted fns)."""

    max_slots: int = 4
    # pool positions per slot; 0 = the model's max_len. For PagedEngine
    # this sizes the DEFAULTS of the block pool (num_blocks /
    # max_blocks_per_slot below), not a hard span — per-slot capacity is
    # max_blocks_per_slot * block_size and may exceed the model's
    # max_len (RoPE positions are unbounded).
    max_len: int = 0
    # LEFT-pad prompt widths for the bucketed prefill compile cache; the
    # largest bucket is also the base cursor (admission always has room
    # to place a full-width prompt behind the cursor)
    prompt_buckets: Tuple[int, ...] = (8, 16, 32, 64)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    eos_id: Optional[int] = None
    pad_id: int = 0
    # decode steps per dispatch (multi-step scheduling): a lax.scan of K
    # single-token steps amortizes the per-dispatch host overhead K-fold
    # at the cost of slot-release granularity — a request finishing
    # mid-burst holds its slot (and the scheduler discards its surplus
    # tokens) until the burst boundary, E[K/2] wasted slot-steps per
    # request vs the static baseline's E[max - asked]. K=1 is exact
    # token-granularity scheduling (the deterministic-test setting).
    decode_burst: int = 1
    # ---- PagedEngine knobs (ignored by SlotEngine) ----
    # positions per pool block; the allocation granule. Multiples of 8
    # keep the TPU kernel's sublane tiling happy (ops/decode_attention).
    block_size: int = 16
    # pool blocks; 0 = 1 garbage block + max_slots * max_blocks_per_slot
    # (full backing — every slot can reach its capacity simultaneously).
    # Set smaller to oversubscribe (admission then gates on blocks).
    num_blocks: int = 0
    # per-slot page-table width = context cap in blocks; 0 =
    # ceil(max_len / block_size). THIS is a slot's attention span — size
    # it to the workload's real contexts, not the pool.
    max_blocks_per_slot: int = 0
    # radix prefix cache over the block pool (serve/kv_pages.py
    # RadixPrefixCache): admissions whose prompt prefix is already
    # resident share those blocks refcounted and prefill only the
    # suffix. Changes the admission layout from left-padded to
    # canonical right-padded positions (sharing needs every request to
    # agree where token i of a prefix lives), so the prefill program is
    # `_prefix_prefill`, not the scratch+scatter pair — greedy tokens
    # stay equivalent (RoPE; pinned in tests/test_serve_equivalence.py).
    prefix_cache: bool = False
    # ---- speculative decoding (PagedEngine only, greedy only) ----
    # draft-free speculation (serve/spec.py): a host-side prompt-lookup
    # drafter proposes up to spec_k tokens per slot and ONE jitted
    # verify dispatch (`step_verify`) scores the whole window — a short
    # paged prefill — accepting the longest prefix that matches the
    # model's own argmaxes plus one corrected token. Greedy-exact:
    # emitted tokens are what plain decode would have produced, so this
    # is purely a latency lever. Requires temperature == 0.0 (exact
    # acceptance IS greedy string matching).
    spec_decode: bool = False
    # drafted window length per verify dispatch (tokens per proposal)
    spec_k: int = 4
    # prompt-lookup n-gram match lengths, tried longest-first
    spec_ngram_max: int = 3
    spec_ngram_min: int = 1
    # ---- per-slot sampling (both engines) ----
    # temperature / top_k / top_p stop being compile-time constants:
    # every slot carries its own (temp, k, p) in small device arrays
    # shipped per dispatch (like the page table), and the decode
    # program samples through inference.sample_logits_batch — ONE
    # jitted program serves a batch mixing greedy and sampled requests,
    # and a request's params can never cause a recompile. Slots get
    # their params at admit (`admit(..., sampling=(t, k, p))`, None
    # fields falling back to the config values above). Excludes
    # spec_decode: exact acceptance is greedy string matching, which
    # per-request temperatures would break.
    per_slot_sampling: bool = False
    # ---- chunked prefill (PagedEngine + prefix_cache only) ----
    # split long COLD prompts into chunks of at most this many tokens,
    # prefilled one chunk per scheduler tick interleaved with decode
    # bursts (Sarathi-style): a long admit no longer stalls every
    # running stream for its whole prefill, so TTFT jitter is bounded
    # by one chunk's forward instead of the longest prompt's. 0 = off
    # (whole-prompt admission, the pre-16 behavior). Chunks ride the
    # `_prefix_prefill` program at canonical right-padded slot-local
    # positions — which is why prefix_cache is required — and a prompt
    # may now EXCEED the largest bucket: servability is bounded by the
    # per-slot block capacity, not the bucket table.
    prefill_chunk: int = 0


def _sample_step(cfg: EngineConfig, last_logits, active, keys,
                 sampling=None):
    """One sampling step shared by both engines: per-slot PRNG chains,
    greedy fast path, pad tokens for free slots. Returns
    (tokens int32, new_keys).

    `sampling` is None (params baked from cfg — the legacy single-
    compile path, pytree-empty so it costs no trace arg) or a triple of
    traced (s,) arrays (temperature, top_k, top_p) — the
    per_slot_sampling path, where every slot samples under its own
    params via sample_logits_batch and the key chains ALWAYS advance
    (greedy rows discard their draw), so a request's stream never
    depends on its batchmates' params."""
    if sampling is not None:
        temp, tk, tp = sampling
        split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
        subs, new_keys = split[:, 0], split[:, 1]
        toks = sample_logits_batch(
            last_logits, subs, temperature=temp, top_k=tk, top_p=tp
        )
    elif cfg.temperature == 0.0:
        toks = sample_logits(last_logits, None, temperature=0.0)
        new_keys = keys
    else:
        split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
        subs, new_keys = split[:, 0], split[:, 1]
        toks = jax.vmap(
            lambda lg, k: sample_logits(
                lg[None], k, temperature=cfg.temperature,
                top_k=cfg.top_k, top_p=cfg.top_p,
            )[0]
        )(last_logits, subs)
    toks = jnp.where(
        active, toks.astype(jnp.int32), jnp.int32(cfg.pad_id)
    )
    return toks, new_keys


def _decode_donate(pool_argnum: int = 1) -> tuple:
    """donate_argnums for a pool-rewriting dispatch: the cache pool
    (arg 1 after params for decode, arg 0 for the CoW copy) is donated
    on TPU so XLA reuses its HBM in place — with a paged pool the buffer
    is the whole serving memory, big enough to care (ROADMAP
    engine-level item). Gated off on CPU, where donation is
    unimplemented and every dispatch would warn."""
    return (pool_argnum,) if jax.default_backend() == "tpu" else ()


_CPU_DISPATCH_BARRIER = None


def _await_dispatch(*state) -> None:
    """Block until a dispatch's outputs are fully materialized — CPU
    backend only.

    XLA:CPU's thunk runtime can report a dispatch's small outputs
    (tokens, logits) ready while writes into the big cache buffers are
    still in flight; chaining the next dispatch off that state races
    the tail of the previous one, and the corrupted reads flip near-tie
    argmaxes run to run. One barrier per dispatch restores
    bit-determinism — every token-identity pin and bench identity gate
    in this repo relies on it. (Empirically: fresh engines replaying
    the same trace diverged with logit deltas of O(0.1-1), far beyond
    FP reassociation noise, and a block_until_ready on the dispatch
    state makes the divergence vanish.) On TPU execution is
    stream-ordered per core, so the barrier would only break dispatch
    pipelining — skip it.
    """
    global _CPU_DISPATCH_BARRIER
    if _CPU_DISPATCH_BARRIER is None:
        _CPU_DISPATCH_BARRIER = jax.default_backend() == "cpu"
    if _CPU_DISPATCH_BARRIER:
        jax.block_until_ready(state)


def warm_engine(engine, widths=None) -> None:
    """Compile an engine's programs outside any timed/traced window:
    one admit per bucket width in play + one decode burst, then release
    and rewind. THE one warmup recipe — the in-process router's
    ReplicaHandle and the worker process (serve/worker.py) both call
    it, so a restarted replica re-warms exactly like a fresh one.
    The admit budgets only the one warmup burst: a paged engine's
    default admit reserves its whole per-slot capacity, which an
    oversubscribed block pool can't cover even though the gated
    scheduler path serves it fine."""
    for w in widths or engine.buckets:
        slot = engine.admit([1] * w,
                            max_positions=engine.config.decode_burst)
        # chunk-admitted prompts (prefill_chunk) activate only once
        # every chunk has run — drive the chunk program to completion
        # so its compiles land in warmup too
        while getattr(engine, "is_prefilling", lambda s: False)(slot):
            engine.prefill_step(slot)
        engine.step_burst()
        engine.release(slot)
    if getattr(engine, "drafter", None) is not None:
        # speculation on: the verify program is a THIRD compile that
        # must also land outside the timed/traced window. An all-ones
        # prompt makes the lookup drafter propose a full window (every
        # trailing n-gram recurs), so the real verify shape compiles.
        slot = engine.admit([1] * engine.buckets[0],
                            max_positions=engine.config.spec_k + 1)
        drafts, draft_lens, _ = engine.propose_drafts()
        engine.step_verify(drafts, draft_lens)
        engine.release(slot)
        # the warm dispatch must not pollute the metrics plane: flight
        # records and the delta-exported counters both reconcile against
        # these cumulative fields, and warmup tokens belong to no request
        engine.spec_drafted_tokens = 0
        engine.spec_accepted_tokens = 0
        engine.spec_dispatches = 0
    engine.reset_epoch()


class _EngineBase:
    """What the two memory layouts share: the prompt-bucket map, slot
    accounting over a SlotAllocator at `self.allocator`, the
    token-granular `step()` veneer over `step_burst`, the
    two-jitted-programs observable (`self._prefill_jit` /
    `self._decode_jit` set by each subclass __init__), and the optional
    tracer (`set_tracer`): per-dispatch prefill / decode-burst lane
    spans plus `jax.profiler.TraceAnnotation` regions NAMED with the
    dispatch's trace-ids, so a device trace (utils/profiling.py ->
    utils/xprof.py) lines up with the host spans. tracer=None (default)
    keeps the dispatch path annotation-free."""

    # set by each subclass __init__ via set_tracer defaults
    tracer = None
    replica = 0
    # per-burst surfacing for the streaming plane: how many decode
    # dispatches this engine ever ran and how many slots were live in
    # the last one. The scheduler stamps `burst_seq` onto each
    # TokenChunk's telemetry line, so per-chunk flight accounting can
    # tell "no bursts ran" (a stalled engine) from "bursts ran without
    # this request" (preempted / queued) when attributing a resume gap.
    burst_seq = 0
    last_burst_active = 0

    def set_tracer(self, tracer, replica: int = 0) -> None:
        """Attach a utils/trace.py TraceRecorder; `replica` is this
        engine's pid in the exported timeline (lane conventions:
        trace.label_replica)."""
        self.tracer = tracer
        self.replica = replica

    def _dispatch_ids(self) -> list:
        """Active slots' trace-ids in slot order (decode annotation)."""
        return [self._slot_trace.get(s, f"slot{s}")
                for s in np.flatnonzero(self._active)]

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest bucket width holding `prompt_len` (raises if none)."""
        for w in self.buckets:
            if prompt_len <= w:
                return w
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )

    def fits_prompt(self, prompt_len: int) -> bool:
        """Can this engine EVER serve a prompt of this length? The
        feasibility probe the router's salvage/failover path asks
        before re-targeting a request — bucket-bounded here; the
        chunk-capable PagedEngine overrides it with a capacity bound."""
        try:
            self.bucket_for(prompt_len)
            return True
        except ValueError:
            return False

    def _sampling_args(self):
        """Per-slot sampling params for the next decode dispatch: a
        triple of (s,) device arrays when per_slot_sampling, else None.
        None is an EMPTY pytree, so the legacy path's decode program
        keeps its single compile and the per-slot path adds exactly
        one — the churn pins (compile_stats) cover both."""
        if not self.config.per_slot_sampling:
            return None
        return (jnp.asarray(self._temp), jnp.asarray(self._topk),
                jnp.asarray(self._topp))

    def _set_sampling(self, slot: int, sampling) -> None:
        """Record a slot's sampling params at admit. `sampling` is
        (temperature, top_k, top_p) with None fields falling back to
        the engine config — the scheduler passes a request's overrides
        verbatim. Overrides without per_slot_sampling raise: silently
        sampling at the WRONG params is the one outcome this must
        never produce (the decode program bakes the config values in)."""
        cfg = self.config
        t, k, p = sampling if sampling is not None else (None, None, None)
        t = cfg.temperature if t is None else float(t)
        k = cfg.top_k if k is None else int(k)
        p = cfg.top_p if p is None else float(p)
        if not cfg.per_slot_sampling and (
                t != cfg.temperature or k != cfg.top_k
                or p != cfg.top_p):
            raise ValueError(
                "per-request sampling params need "
                "EngineConfig.per_slot_sampling=True"
            )
        self._temp[slot] = t
        self._topk[slot] = k
        self._topp[slot] = p

    @property
    def num_active(self) -> int:
        return self.allocator.num_used

    @property
    def num_free(self) -> int:
        return self.allocator.num_free

    def step(self) -> np.ndarray:
        """One decode step for the whole pool; tokens (max_slots,).
        Token-granular stepping — requires decode_burst=1 (use
        step_burst for the amortized path)."""
        if self.config.decode_burst != 1:
            raise RuntimeError("step() needs decode_burst=1")
        return self.step_burst()[0]

    def compile_stats(self) -> dict:
        """Jit cache sizes — the no-recompilation-churn observable.

        After warmup (one admit per bucket width in play, one decode
        dispatch), these counts must stay CONSTANT however many requests
        churn through (pinned via the conftest `compile_guard` helper
        and tests/test_serve_scheduler.py)."""
        return {
            "prefill_compiles": self._prefill_jit._cache_size(),
            "decode_compiles": self._decode_jit._cache_size(),
        }


class SlotEngine(_EngineBase):
    """Slot-granular admission + batched single-token decode.

    Pure mechanism: WHAT to admit/release and WHEN is the scheduler's
    job (serve/scheduler.py); this class owns the device state (cache
    pool, last-logits, attention starts, per-slot PRNG keys) and the two
    jitted programs. All host<->device traffic per step is one token
    vector readback.
    """

    def __init__(self, model, params, config: EngineConfig = EngineConfig(),
                 *, batch_stats: Any = None) -> None:
        if getattr(model, "pos_emb", None) != "rope":
            raise ValueError(
                "SlotEngine needs pos_emb='rope' — slot admission "
                "left-aligns prompts at arbitrary cache offsets, which "
                "only relative positions survive (models/lm.py attn_start)"
            )
        if not config.prompt_buckets:
            raise ValueError("prompt_buckets must be non-empty")
        if config.spec_decode:
            raise ValueError(
                "spec_decode needs PagedEngine — the verify window is a "
                "paged prefill through per-slot page tables, which the "
                "shared-cursor slot pool cannot express"
            )
        if config.prefill_chunk:
            raise ValueError(
                "prefill_chunk needs PagedEngine with prefix_cache — "
                "chunks append at canonical slot-local positions "
                "through the page table, which the shared-cursor slot "
                "pool cannot express"
            )
        self.model = model
        self.params = params
        self.batch_stats = batch_stats
        self.config = config
        self.max_len = config.max_len or model.max_len
        self.buckets = tuple(sorted(set(config.prompt_buckets)))
        self.base_cursor = self.buckets[-1]
        if self.base_cursor >= self.max_len:
            raise ValueError(
                f"largest prompt bucket {self.base_cursor} leaves no decode "
                f"headroom in max_len {self.max_len}"
            )
        s = config.max_slots
        self.allocator = SlotAllocator(s)
        self.cursor = self.base_cursor  # host mirror of the device cursor
        self._cache = set_cursor(
            make_cache(model, s, self.max_len), self.base_cursor
        )
        self._last_logits = jnp.zeros((s, model.vocab_size), model.dtype)
        self._attn_starts = jnp.zeros((s,), jnp.int32)
        self._keys = jnp.zeros((s, 2), jnp.uint32)
        self._active = np.zeros((s,), bool)
        # per-slot sampling mirrors (host side, shipped per dispatch
        # like _active when per_slot_sampling is on); config-filled so
        # a slot admitted without overrides samples exactly as before
        self._temp = np.full((s,), config.temperature, np.float32)
        self._topk = np.full((s,), config.top_k, np.int32)
        self._topp = np.full((s,), config.top_p, np.float32)
        self.last_finite = np.ones((1, s), bool)  # updated per step_burst
        self._slot_trace: dict = {}  # slot -> trace_id (tracer attached)
        if config.decode_burst < 1:
            raise ValueError("decode_burst must be >= 1")
        self._prefill_jit = jax.jit(self._prefill_admit)
        self._decode_jit = jax.jit(
            self._decode_burst, donate_argnums=_decode_donate()
        )

    # ---------------------------------------------------------------- jitted
    def _prefill_admit(self, params, pool, last_logits, attn_starts,
                       tokens, start, attn_start, slot):
        """tokens (1, w) left-padded; start = cursor - w; one compile per w."""
        scratch = set_cursor(make_cache(self.model, 1, self.max_len), start)
        scratch, logits = decode_apply(
            self.model, params, scratch, tokens,
            attn_start=attn_start[None], batch_stats=self.batch_stats,
        )
        pool = write_slot(pool, scratch, slot)
        last_logits = lax.dynamic_update_slice(
            last_logits, logits[:, -1].astype(last_logits.dtype), (slot, 0)
        )
        attn_starts = lax.dynamic_update_slice(
            attn_starts, attn_start[None], (slot,)
        )
        return pool, last_logits, attn_starts

    def _decode_body(self, params, pool, last_logits, attn_starts,
                     active, keys, sampling):
        cfg = self.config
        # per-slot finite-logits flag, computed on the SAMPLING INPUT: a
        # non-finite row (bf16 overflow, poisoned cache) marks only its
        # own slot — attention is per-row, so the NaN cannot cross slots,
        # and this flag is what lets the scheduler finish ONE request
        # with status "error" instead of serving garbage batch-wide
        finite = jnp.isfinite(last_logits).all(axis=-1)
        toks, new_keys = _sample_step(cfg, last_logits, active, keys,
                                      sampling)
        pool, logits = decode_apply(
            self.model, params, pool, toks[:, None],
            attn_start=attn_starts, batch_stats=self.batch_stats,
        )
        return pool, logits[:, -1], toks, new_keys, finite

    def _decode_burst(self, params, pool, last_logits, attn_starts,
                      active, keys, sampling):
        """lax.scan of `decode_burst` single-token steps per dispatch —
        the host-overhead amortizer (multi-step scheduling). Returns
        tokens (K, max_slots); K=1 is plain token-granular stepping."""

        def body(carry, _):
            pool, last_logits, keys = carry
            pool, last_logits, toks, keys, finite = self._decode_body(
                params, pool, last_logits, attn_starts, active, keys,
                sampling,
            )
            return (pool, last_logits, keys), (toks, finite)

        (pool, last_logits, keys), (toks, finite) = lax.scan(
            body, (pool, last_logits, keys), None,
            length=self.config.decode_burst,
        )
        return pool, last_logits, toks, keys, finite

    # ----------------------------------------------------------------- host
    @property
    def headroom(self) -> int:
        """Decode positions left before the pool cursor hits max_len."""
        return self.max_len - self.cursor

    def admit_gate(self, prompt_len: int, needed_positions: int,
                   prompt: Optional[Sequence[int]] = None) -> str:
        """Admission verdict for a request needing `needed_positions`
        decode positions (burst-rounded by the scheduler):
        "ok" = admit now; "later" = cannot yet (positions will free —
        here, after a drain + `make_room` rewind); "never" = can never
        run on this engine (prompt outgrows every bucket, or more
        positions than a fresh pool holds). `prompt` is accepted for
        interface parity with PagedEngine (whose prefix cache probes
        the tokens themselves) and ignored here."""
        try:
            self.bucket_for(prompt_len)
        except ValueError:
            return "never"
        if needed_positions > self.max_len - self.base_cursor:
            return "never"
        if self.headroom < needed_positions:
            return "later"
        return "ok"

    def make_room(self, prompt_len: Optional[int] = None,
                  needed_positions: Optional[int] = None,
                  prompt: Optional[Sequence[int]] = None) -> bool:
        """Try to create admission headroom; True if anything changed.
        Positions are a global resource under the shared cursor — the
        only lever is rewinding the pool clock once every slot is free
        (the scheduler drains, then calls this), so the blocked
        request's shape (used by PagedEngine for targeted cache aging)
        is accepted for interface parity and ignored. The paged engine
        has no drain equivalent: its blocks free individually at
        release."""
        if self.allocator.num_used == 0 and self.cursor != self.base_cursor:
            self.reset_epoch()
            return True
        return False

    def admit(self, prompt: Sequence[int], *, seed: int = 0,
              max_positions: Optional[int] = None,
              trace_id: Optional[str] = None,
              sampling: Optional[Tuple] = None) -> int:
        """Prefill `prompt` into a free slot; returns the slot index.

        The prompt joins exactly where the running batch is: its last
        token's K/V lands at `cursor - 1`, so the NEXT decode step
        produces its first generated token together with everyone
        else's. Raises if no slot is free or the prompt outgrows the
        buckets — admission POLICY (queueing, shedding) lives in the
        scheduler. `max_positions` is accepted for engine-interface
        parity with PagedEngine (which reserves blocks per request) and
        ignored here: slot-pool positions are a global resource.
        `trace_id` names the prefill span / profiler annotation when a
        tracer is attached. `sampling` = per-request (temperature,
        top_k, top_p) overrides, None fields defaulting to the config
        (needs EngineConfig.per_slot_sampling).
        """
        p = len(prompt)
        if p == 0:
            raise ValueError("prompt must contain at least one token")
        w = self.bucket_for(p)
        slot = self.allocator.alloc()
        if slot is None:
            raise RuntimeError("no free slot — scheduler must gate admits")
        try:
            self._set_sampling(slot, sampling)
        except ValueError:
            self.allocator.free(slot)
            raise
        start = self.cursor - w
        assert start >= 0, (self.cursor, w)  # cursor >= base >= every bucket
        padded = np.full((1, w), self.config.pad_id, np.int32)
        padded[0, w - p:] = np.asarray(prompt, np.int32)
        tr = self.tracer
        if tr is not None and tr.enabled:
            tid = trace_id or f"slot{slot}"
            self._slot_trace[slot] = tid
            span = tr.span("prefill", trace_id=tid, pid=self.replica,
                           tid=SLOT_LANE_BASE + slot, bucket=w,
                           prompt_len=p, slot=slot)
            ann = jax.profiler.TraceAnnotation(f"serve:prefill:{tid}")
        else:
            span = ann = _NULL
        with span, ann:
            (self._cache, self._last_logits,
             self._attn_starts) = self._prefill_jit(
                self.params, self._cache, self._last_logits,
                self._attn_starts,
                jnp.asarray(padded), jnp.int32(start),
                jnp.int32(self.cursor - p), jnp.int32(slot),
            )
            _await_dispatch(self._cache, self._last_logits,
                            self._attn_starts)
        # keyed by the REQUEST's seed alone (not the slot), so a
        # request's sampled tokens are independent of where admission
        # happened to place it — batch composition stays invisible
        self._keys = self._keys.at[slot].set(jax.random.PRNGKey(seed))
        self._active[slot] = True
        return slot

    def step_burst(self) -> np.ndarray:
        """One dispatch of `decode_burst` steps; tokens (K, max_slots).

        Advances the shared cursor by K positions. Entries for free
        slots are pad_id; the scheduler maps active slots' token rows
        back to their requests, decides EOS/length/deadline release,
        and discards rows past a request's release point.
        """
        k = self.config.decode_burst
        if self.headroom < k:
            raise RuntimeError(
                "pool positions exhausted — drain and reset_epoch()"
            )
        tr = self.tracer
        if tr is not None and tr.enabled:
            ids = self._dispatch_ids()
            # sampled_only: the burst span names no trace_id (it is a
            # shared engine-lane record), so under head sampling it is
            # kept only while some SAMPLED request is in flight —
            # otherwise an idle 1%-sampled fleet would still record a
            # span per burst and the plane would never shrink
            span = tr.span("decode_burst", pid=self.replica,
                           tid=ENGINE_LANE, burst=k, active=len(ids),
                           cursor=self.cursor, sampled_only=True)
            ann = jax.profiler.TraceAnnotation(
                "serve:decode[" + ",".join(ids) + "]"
            )
        else:
            span = ann = _NULL
        with span, ann:
            (self._cache, self._last_logits, toks,
             self._keys, finite) = self._decode_jit(
                self.params, self._cache, self._last_logits,
                self._attn_starts,
                jnp.asarray(self._active), self._keys,
                self._sampling_args(),
            )
            _await_dispatch(self._cache, self._last_logits, self._keys)
            self.cursor += k
            toks, finite = jax.device_get((toks, finite))
        self.burst_seq += 1
        self.last_burst_active = int(np.count_nonzero(self._active))
        # (K, max_slots) bool: False rows mark slots whose token this
        # burst was sampled from non-finite logits — the scheduler
        # finishes those requests with status "error"
        self.last_finite = np.asarray(finite)
        return np.asarray(toks)

    def poison_slot(self, slot: int) -> None:
        """Overwrite one slot's pending sampling input with NaN — the
        deterministic stand-in for a numerical blow-up (serve/faults.py
        `nan_logits`). Host-side, between dispatches; the next decode
        burst's finite flag turns False for exactly this slot."""
        self._last_logits = self._last_logits.at[slot].set(jnp.nan)

    def release(self, slot: int) -> None:
        """Free a slot. Pure bookkeeping: the next admission overwrites
        the slot's entire cache row (kv_slots.write_slot), so no device
        work happens at release time."""
        self.allocator.free(slot)
        self._active[slot] = False
        self._slot_trace.pop(slot, None)

    def reset_epoch(self) -> None:
        """Rewind the shared cursor to the base (all slots must be free).

        Positions are a global resource under the shared-cursor design;
        when the scheduler has drained all active requests it rewinds
        the clock instead of reallocating the pool. Stale K/V stays in
        the buffers — every future admission wipes its whole slot row.
        """
        if self.allocator.num_used:
            raise RuntimeError("reset_epoch with active slots")
        self._cache = set_cursor(self._cache, self.base_cursor)
        self._attn_starts = jnp.zeros_like(self._attn_starts)
        self.cursor = self.base_cursor


class PagedEngine(_EngineBase):
    """Paged-KV continuous batching: per-slot page tables, no shared clock.

    Same two-jitted-programs contract and public surface as SlotEngine
    (the scheduler drives either through `admit_gate` / `admit` /
    `step_burst` / `release`), but the cache is a pool of fixed-size
    blocks (serve/kv_pages.py) and every slot decodes at its OWN
    slot-local write position:

    - `admit` prefills the bucketed prompt into a batch-1 contiguous
      scratch cache at positions [0, w) and scatters it into freshly
      allocated blocks (one compile per bucket width, as before);
    - `step_burst` appends each active slot's token at `lengths[slot]`
      through the page table and attends only that slot's occupied
      pages (ops/decode_attention.paged_decode_attention) — a step's
      attention span is the request's own context, not a pool-global
      [0, max_len);
    - `release` DEREFS the slot's blocks (serve/kv_pages.py refcounts):
      a block shared with the prefix cache or a fork sibling survives,
      a sole-owned one returns to the free list. Nothing ever drains
      and nothing rewinds (no reset_epoch here);
    - a request may decode past the model's / slot engine's max_len:
      per-slot capacity is `max_blocks_per_slot * block_size` and RoPE
      positions are unbounded.

    PR 6 turned the pool into a MULTIPLIER instead of a partition:

    - **Prefix sharing** (`EngineConfig.prefix_cache`): admission walks
      a radix tree of previously served prompt blocks; matched blocks
      join the new slot's page table refcounted and only the prompt
      SUFFIX is prefilled (`_prefix_prefill`, one compile per suffix
      bucket — the hit's prefill chunks are skipped entirely). Sharing
      needs canonical slot-local positions, so this mode right-pads
      (attn_start 0) instead of left-padding.
    - **Copy-on-write**: before a burst writes into a block some other
      holder also references (a fork sibling's tail block), the block
      is first copied into a private one (`copy_block`, one compile
      ever) — which is what makes `fork` (n>1 sampling per prompt)
      memory-cheap: siblings share every prefix block and split only
      where they diverge.
    - **Block-aware preemption** replaces the PR-3 worst-case admission
      reservation: admission takes only the prompt blocks, and when
      growth finds the pool empty the engine first evicts unreferenced
      prefix-cache blocks (LRU), then preempts the YOUNGEST-admitted
      slot — its non-shared blocks free, the victim lands in
      `take_preempted()` and the scheduler re-prefills it on
      readmission (serve/scheduler.py). Admission at the same pool goes
      up because nobody holds blocks they may never use; the solo-fit
      admission gate ("never" when a request outgrows the whole pool)
      keeps the preemption cascade terminating.
    """

    def __init__(self, model, params, config: EngineConfig = EngineConfig(),
                 *, batch_stats: Any = None,
                 draft_source: Optional[DraftSource] = None) -> None:
        if getattr(model, "pos_emb", None) != "rope":
            raise ValueError(
                "PagedEngine needs pos_emb='rope' — slots decode at "
                "slot-local positions, which only relative positions "
                "survive (models/lm.py)"
            )
        if not config.prompt_buckets:
            raise ValueError("prompt_buckets must be non-empty")
        if config.decode_burst < 1:
            raise ValueError("decode_burst must be >= 1")
        if config.block_size < 1:
            raise ValueError("block_size must be positive")
        if config.spec_decode:
            if config.temperature != 0.0:
                raise ValueError(
                    "spec_decode needs temperature=0.0 — exact "
                    "acceptance is greedy string matching against the "
                    "model's own argmaxes (serve/spec.py)"
                )
            if config.spec_k < 1:
                raise ValueError("spec_k must be >= 1")
            if config.per_slot_sampling:
                raise ValueError(
                    "spec_decode excludes per_slot_sampling — exact "
                    "acceptance is greedy string matching, which a "
                    "slot sampling at its own temperature would break"
                )
        if config.prefill_chunk:
            if not config.prefix_cache:
                raise ValueError(
                    "prefill_chunk needs prefix_cache=True — chunks "
                    "append at canonical right-padded positions through "
                    "the page table (_prefix_prefill), the layout only "
                    "the prefix-cache mode uses"
                )
            if config.prefill_chunk < 1:
                raise ValueError("prefill_chunk must be >= 1 (0 = off)")
            if config.prefill_chunk > max(config.prompt_buckets):
                raise ValueError(
                    f"prefill_chunk {config.prefill_chunk} exceeds the "
                    f"largest prompt bucket "
                    f"{max(config.prompt_buckets)} — each chunk is "
                    f"bucketed for the prefill compile cache"
                )
        self.model = model
        self.params = params
        self.batch_stats = batch_stats
        self.config = config
        self.max_len = config.max_len or model.max_len
        self.buckets = tuple(sorted(set(config.prompt_buckets)))
        bs = config.block_size
        self.max_blocks_per_slot = (
            config.max_blocks_per_slot or -(-self.max_len // bs)
        )
        self.max_context = self.max_blocks_per_slot * bs
        if self.buckets[-1] > min(self.max_context - 1, model.max_len):
            raise ValueError(
                f"largest prompt bucket {self.buckets[-1]} must fit the "
                f"scratch prefill (model max_len {model.max_len}) and "
                f"leave decode room in the per-slot capacity "
                f"{self.max_context}"
            )
        s = config.max_slots
        num_blocks = (
            config.num_blocks or 1 + s * self.max_blocks_per_slot
        )
        self.allocator = SlotAllocator(s)     # slot ids (metrics reads it)
        self.blocks = BlockAllocator(num_blocks)
        self.radix = (
            RadixPrefixCache(self.blocks, bs) if config.prefix_cache
            else None
        )
        # matched tokens of the MOST RECENT admit (None = no prefix
        # cache): the scheduler reads this right after admit() to book
        # prefix_hit_tokens into the request's flight record
        self.last_prefix_hit: Optional[int] = None
        self._cache = make_paged_cache(model, num_blocks, bs)
        self._last_logits = jnp.zeros((s, model.vocab_size), model.dtype)
        self._keys = jnp.zeros((s, 2), jnp.uint32)
        self._active = np.zeros((s,), bool)
        # per-slot sampling mirrors — same contract as SlotEngine's
        self._temp = np.full((s,), config.temperature, np.float32)
        self._topk = np.full((s,), config.top_k, np.int32)
        self._topp = np.full((s,), config.top_p, np.float32)
        # chunk-admitted slots mid-prefill: slot -> {"prompt", "done"}.
        # The slot holds blocks and a page table but stays INACTIVE
        # (decode bursts pad it, preemption never picks it) until
        # prefill_step lands the final chunk.
        self._pending_prompt: dict = {}
        # host-side per-slot state; tiny, shipped to device per dispatch
        self._pt = np.zeros((s, self.max_blocks_per_slot), np.int32)
        self._len = np.zeros((s,), np.int32)
        self._attn = np.zeros((s,), np.int32)
        self._nblk = np.zeros((s,), np.int64)   # blocks in the table
        self._budget = np.zeros((s,), np.int64)  # admit-time block cap
        self._seq = np.zeros((s,), np.int64)     # admission order (LIFO
        self._admit_seq = 0                      # preemption victims)
        self._preempted: list = []   # slots evicted since last drain
        self.preemptions = 0         # cumulative (metrics export)
        self.last_finite = np.ones((1, s), bool)
        self._slot_trace: dict = {}  # slot -> trace_id (tracer attached)
        # replayable fork seeds: slot -> the request's SEED PATH — the
        # admit seed plus one fork ordinal per ancestor fork, e.g.
        # (seed,) for an admitted request, (seed, 2) for its second
        # fork child. fork() derives the child key by folding the path,
        # so a sibling's sample stream is a function of (admit seed,
        # fork order) alone — replayable across slot layouts and
        # independent of how many decode steps ran before the fork.
        self._slot_seed: dict = {}
        self._fork_n: dict = {}      # slot -> forks taken off it so far
        # speculative decoding (serve/spec.py): the host-side drafter
        # tracks every slot's context; its proposals feed step_verify.
        # Cumulative counters are the metrics-plane observable
        # (delta-exported by serve/metrics.py, same idiom as
        # `preemptions`).
        if config.spec_decode:
            self.drafter: Optional[DraftSource] = (
                draft_source if draft_source is not None
                else PromptLookupDraft(config.spec_ngram_max,
                                       config.spec_ngram_min)
            )
        else:
            self.drafter = None
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_dispatches = 0
        self._prefill_jit = jax.jit(self._prefill_admit)
        self._decode_jit = jax.jit(
            self._decode_burst, donate_argnums=_decode_donate()
        )
        # the verify program (speculative decoding): one compile for the
        # (max_slots, spec_k) window shape, always in compile_stats so
        # the churn pins cover it even before the first dispatch
        self._verify_jit = jax.jit(
            self._verify, donate_argnums=_decode_donate()
        )
        # prefix-mode suffix prefill (one compile per suffix bucket) and
        # the copy-on-write block split (one compile, ever) — both in
        # compile_stats so the churn pins cover the new admission paths
        self._prefix_jit = jax.jit(self._prefix_prefill)
        self._cow_jit = jax.jit(
            copy_block, donate_argnums=_decode_donate(pool_argnum=0)
        )
        self._fork_jit = jax.jit(self._fork_rows)

    # ---------------------------------------------------------------- jitted
    def _prefill_admit(self, params, pool, last_logits, tokens,
                       attn_start, block_ids, slot):
        """tokens (1, w) left-padded; one compile per bucket width w.

        The scratch cache starts at cursor 0 — slot-local coordinates —
        so admission is placement-free: no alignment to anyone else's
        cursor, just a scatter of the w prefilled rows into this slot's
        blocks."""
        w = tokens.shape[1]
        scratch = make_cache(self.model, 1, w)
        scratch, logits = decode_apply(
            self.model, params, scratch, tokens,
            attn_start=attn_start[None], batch_stats=self.batch_stats,
        )
        pool = scatter_prompt_blocks(
            pool, scratch, block_ids, w, self.config.block_size
        )
        last_logits = lax.dynamic_update_slice(
            last_logits, logits[:, -1].astype(last_logits.dtype), (slot, 0)
        )
        return pool, last_logits

    def _prefix_prefill(self, params, pool, last_logits, tokens,
                        pos0, true_len, pt_row, slot):
        """Prefix-cache admission prefill: tokens (1, w) RIGHT-padded —
        the real suffix in rows [0, true_len) — appended at slot-local
        positions [pos0, pos0+w) THROUGH the page table, attending the
        shared prefix blocks [0, pos0) in place (models/vit.py paged
        s>1 path). One compile per suffix bucket width w. The pad rows
        write garbage K/V at positions past the context, which the
        causal mask hides until decode overwrites them; the next-token
        logits are the last REAL row's (dynamic true_len - 1)."""
        pool, logits = decode_apply(
            self.model, params, pool, tokens,
            batch_stats=self.batch_stats,
            page_table=pt_row, kv_lengths=pos0[None],
        )
        last = lax.dynamic_slice(
            logits, (0, true_len - 1, 0), (1, 1, logits.shape[2])
        )[:, 0]
        last_logits = lax.dynamic_update_slice(
            last_logits, last.astype(last_logits.dtype), (slot, 0)
        )
        return pool, last_logits

    @staticmethod
    def _fork_rows(last_logits, keys, src, dst, key):
        """Duplicate one slot's carried sampling state into another
        (fork): same pending logits, a FRESH PRNG chain — siblings
        diverge by sampling, not by context."""
        row = lax.dynamic_slice(
            last_logits, (src, 0), (1, last_logits.shape[1])
        )
        last_logits = lax.dynamic_update_slice(last_logits, row, (dst, 0))
        keys = lax.dynamic_update_slice(keys, key[None], (dst, 0))
        return last_logits, keys

    def _decode_burst(self, params, pool, last_logits, attn_starts,
                      active, keys, page_table, lengths, sampling):
        """lax.scan of `decode_burst` paged single-token steps. Each step
        writes active slots' K/V at their own `lengths` position and
        advances only active lengths; retired slots keep scattering into
        the garbage block (kv_pages.GARBAGE_BLOCK) so shapes stay static."""

        def body(carry, _):
            pool, last_logits, keys, lengths = carry
            finite = jnp.isfinite(last_logits).all(axis=-1)
            toks, keys = _sample_step(self.config, last_logits, active,
                                      keys, sampling)
            pool, logits = decode_apply(
                self.model, params, pool, toks[:, None],
                attn_start=attn_starts, batch_stats=self.batch_stats,
                page_table=page_table, kv_lengths=lengths,
            )
            lengths = lengths + active.astype(lengths.dtype)
            return (pool, logits[:, -1], keys, lengths), (toks, finite)

        (pool, last_logits, keys, _), (toks, finite) = lax.scan(
            body, (pool, last_logits, keys, lengths), None,
            length=self.config.decode_burst,
        )
        return pool, last_logits, toks, keys, finite

    def _verify(self, params, pool, last_logits, attn_starts, active,
                drafts, draft_lens, page_table, lengths):
        """Speculative verify: score a k-token drafted window in ONE
        forward, accept greedily, append one corrected token.

        `drafts` (max_slots, k) are the drafter's proposals for each
        slot's next positions, `draft_lens` how many are real. The
        window forward is a paged PREFILL at positions
        `lengths[b] + [0, k)` (models/vit.py s>1 paged path — the same
        program shape as prefix-cache suffix admission), writing the
        drafted tokens' K/V through the page table.

        Acceptance is exact: stack the carried next-token logits in
        front of the window logits — row i of the stack predicts the
        token at position lengths+i — and take `g = argmax` (the very
        op plain greedy decode runs, inference.sample_logits). Draft
        token i is accepted iff it equals g[:, i] AND every earlier
        draft matched (cumprod); with m accepted, the emitted run is
        `g[:, :m+1]`: the m accepted drafts (which ARE the leading
        argmaxes) plus the model's own token at the first divergence —
        or the bonus token after a fully-accepted window. A final
        fused s=1 decode step writes that correction token's K/V at
        the per-slot position `lengths + m` — overwriting the rejected
        draft's K/V row — and carries its logits as the next sampling
        input.

        Rollback is positional, not a copy: rejected window positions
        `lengths+m+1 .. lengths+k-1` hold garbage K/V inside the
        slot's own blocks, invisible to attention (masked to
        kv_lengths) and overwritten by whatever decodes there next;
        the host side rewinds `kv_lengths` to `lengths + m + 1` and
        returns this dispatch's surplus grown blocks to the pool
        (kv_pages.rewind_block_tail). Free slots ride along on the
        garbage block as in `_decode_burst`.

        Returns (pool, last_logits, g (s, k+1), accepted (s,),
        finite (s, k+1)) — finite row i flags the logits token i was
        argmaxed from, the scheduler's per-token "error" signal.
        """
        k = drafts.shape[1]
        pool, win_logits = decode_apply(
            self.model, params, pool, drafts,
            attn_start=attn_starts, batch_stats=self.batch_stats,
            page_table=page_table, kv_lengths=lengths,
        )
        all_logits = jnp.concatenate(
            [last_logits[:, None], win_logits.astype(last_logits.dtype)],
            axis=1,
        )                                                   # (s, k+1, v)
        g = sample_logits(all_logits, None, temperature=0.0)
        g = g.astype(jnp.int32)                             # (s, k+1)
        matches = (drafts == g[:, :k]) & (
            jnp.arange(k, dtype=jnp.int32)[None, :] < draft_lens[:, None]
        )
        accepted = jnp.cumprod(
            matches.astype(jnp.int32), axis=1
        ).sum(axis=1)                                       # (s,) in [0, k]
        accepted = jnp.where(active, accepted, 0)
        finite = jnp.isfinite(all_logits).all(axis=-1)      # (s, k+1)
        correction = jnp.take_along_axis(g, accepted[:, None], axis=1)
        correction = jnp.where(
            active[:, None], correction, jnp.int32(self.config.pad_id)
        )
        pool, nxt_logits = decode_apply(
            self.model, params, pool, correction,
            attn_start=attn_starts, batch_stats=self.batch_stats,
            page_table=page_table, kv_lengths=lengths + accepted,
        )
        last_logits = jnp.where(
            active[:, None],
            nxt_logits[:, -1].astype(last_logits.dtype), last_logits,
        )
        toks = jnp.where(
            active[:, None], g, jnp.int32(self.config.pad_id)
        )
        return pool, last_logits, toks, accepted, finite

    # ----------------------------------------------------------------- host
    def _blocks_for(self, positions: int) -> int:
        return -(-positions // self.config.block_size)

    @property
    def blocks_available(self) -> int:
        """Blocks admission can promise RIGHT NOW: the free list plus
        unreferenced prefix-cache blocks (evicted on demand). No
        reservation term any more — future growth is backed by releases
        and block-aware preemption, not by up-front hoarding."""
        free = self.blocks.num_free
        if self.radix is not None:
            free += self.radix.evictable()
        return free

    @property
    def headroom(self) -> int:
        """Promisable pool positions (informational — admission gates on
        blocks per request, not on a global span)."""
        return self.blocks_available * self.config.block_size

    def _probe_prefix(self, prompt: Sequence[int]) -> int:
        """Read-only longest-cached-prefix length for `prompt` (0 with
        the cache off) — what the admission gate subtracts before
        bucketing: a prompt whose cached prefix leaves a bucketable
        suffix is servable even when the WHOLE prompt outgrows every
        bucket (long shared system prompts)."""
        if self.radix is None:
            return 0
        return self.radix.peek(prompt)

    def _admit_plan(self, prompt_len: int,
                    prompt: Optional[Sequence[int]] = None):
        """(matched, bucket_w, need_now) for an admission, or None when
        no bucket fits the uncached suffix. need_now = prompt-table
        blocks not already cached + one decode block — THE one place the
        gate, make_room, and preempt_headroom derive it, so the three
        can never disagree on what an admission must take right now.
        With prefill_chunk on, a suffix longer than one chunk is
        bucketed at the CHUNK width (the first chunk is all an
        admission prefills; later chunks grow like decode), so prompts
        past the largest bucket stop being "never"."""
        matched = self._probe_prefix(prompt) if prompt is not None else 0
        suffix = prompt_len - matched
        if self.config.prefill_chunk:
            suffix = min(suffix, self.config.prefill_chunk)
        try:
            w = self.bucket_for(suffix)
        except ValueError:
            return None
        need_now = self._blocks_for(matched + w) \
            - matched // self.config.block_size + 1
        return matched, w, need_now

    def admit_gate(self, prompt_len: int, needed_positions: int,
                   prompt: Optional[Sequence[int]] = None) -> str:
        """"ok" | "later" (blocks free as running requests release, get
        preempted, or prefix blocks age out) | "never" (outgrows every
        bucket even after the cached prefix, the per-slot capacity, or
        the whole pool). Passing the `prompt` itself lets the gate probe
        the prefix cache; without it the gate judges the full length."""
        plan = self._admit_plan(prompt_len, prompt)
        if plan is None:
            return "never"
        matched, w, need_now = plan
        if self.radix is None:
            end = w + needed_positions
        else:
            end = max(matched + w, prompt_len + needed_positions)
        if end > self.max_context:
            return "never"
        if self._blocks_for(end) > self.blocks.num_blocks - 1:
            return "never"  # outgrows the whole pool, even empty
        # prompt blocks now + one decode block; growth is backed by
        # releases / preemption, not a reservation
        if need_now > self.blocks_available:
            return "later"
        return "ok"

    def preempt_headroom(self, slots: Sequence[int], prompt_len: int,
                         prompt: Optional[Sequence[int]] = None) -> bool:
        """Could evicting every slot in `slots` possibly admit a blocked
        request of this shape? Upper bound: a victim's whole table
        surfaces (in truth blocks shared with another RUNNING slot
        stay). False means preemption is pure churn — the scheduler
        skips it and the head just waits for releases."""
        plan = self._admit_plan(prompt_len, prompt)
        if plan is None:
            return False
        bound = self.blocks_available \
            + int(sum(self._nblk[s] for s in slots))
        return plan[2] <= bound

    def make_room(self, prompt_len: Optional[int] = None,
                  needed_positions: Optional[int] = None,
                  prompt: Optional[Sequence[int]] = None) -> bool:
        """Evict unreferenced prefix-cache blocks (LRU) back to the free
        list; True if anything freed. Eviction helps a blocked admission
        only by EXPOSURE: `blocks_available` already counts evictable
        leaves, so the win is interior chain nodes becoming evictable as
        their leaves drop. With the blocked request's shape (the same
        args its admit_gate saw) the pass is TARGETED: the request's own
        matched prefix is pinned first — a blanket evict would consume
        the very blocks that made a long prompt servable, flipping a
        feasible "later" into "never" — and only the shortfall against
        the gate's need is freed, so one blocked tick no longer wipes
        the whole warm cache. Preempting a RUNNING victim for a queued
        request is the scheduler's call (it knows arrival order —
        serve/scheduler.py preempts only young victims for older
        requests, which keeps the cascade terminating); the engine-side
        lever here is only the cache that nobody is attending through."""
        if self.radix is None:
            return False
        keep = self.radix.ref_prefix(prompt) if prompt is not None else []
        try:
            if prompt_len is not None and needed_positions is not None:
                plan = self._admit_plan(prompt_len, prompt)
                if plan is None:
                    return False      # no bucket fits: room cannot help
                # the FULL shortfall, not min(shortfall, evictable()):
                # evictable() counts only current leaves, but evict()'s
                # exposure loop drains interior chain blocks too — a
                # deep single-leaf chain can cover a 3-block shortfall
                want = max(0, plan[2] - self.blocks.num_free)
            else:
                want = self.radix.evictable()
            return want > 0 and self.radix.evict(want) > 0
        finally:
            if keep:
                self.blocks.free(keep)

    # ------------------------------------------------- block acquisition
    def _acquire_admit(self, n: int):
        """n blocks for an admission: free list first, then on-demand
        LRU eviction of unreferenced prefix-cache blocks. Admission
        never preempts runners — the scheduler's gate queues instead."""
        ids = self.blocks.alloc(n)
        if ids is None and self.radix is not None:
            self.radix.evict(n - self.blocks.num_free)
            ids = self.blocks.alloc(n)
        if ids is None:
            raise RuntimeError(
                "not enough free blocks — scheduler must gate admits"
            )
        return ids

    def _acquire_decode(self, n: int, protect: int):
        """n blocks for mid-decode growth / a CoW split: free list, then
        prefix-cache eviction, then BLOCK-AWARE PREEMPTION — evict the
        youngest-admitted active slot's non-shared blocks (LIFO victims,
        vLLM-style) and let the scheduler re-prefill it. `protect` is
        the slot being grown (never preempts itself while older slots
        could yield). Raises only when even preempting everyone else
        cannot cover — impossible for scheduler-gated traffic (the
        "never" gate bounds one request's whole-pool need), reachable by
        direct users who oversubscribe fork budgets."""
        while True:
            ids = self.blocks.alloc(n)
            if ids is not None:
                return ids
            if self.radix is not None \
                    and self.radix.evict(n - self.blocks.num_free):
                continue
            victims = [
                s for s in np.flatnonzero(self._active) if s != protect
            ]
            if not victims:
                raise RuntimeError(
                    f"paged pool exhausted: {n} blocks needed with no "
                    f"victim left to preempt (slot {protect} already "
                    f"holds {int(self._nblk[protect])})"
                )
            victim = max(victims, key=lambda s: self._seq[s])
            self.preempt(int(victim))

    def preempt(self, slot: int) -> None:
        """Evict one active slot: deref its blocks (shared ones — prefix
        blocks, fork siblings' — survive for their other holders), clear
        the slot, and queue it on `take_preempted()` for the scheduler's
        readmission path (re-prefill prompt + generated-so-far).
        Callable by the scheduler (preempt-for-admission) and by
        `_acquire_decode` (growth exhaustion)."""
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not active")
        self._clear_slot(slot)
        self.preemptions += 1
        self._preempted.append(slot)
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.instant("preempt", trace_id=self._slot_trace.get(slot),
                       pid=self.replica, tid=ENGINE_LANE, slot=slot,
                       blocks_free=self.blocks.num_free)
        self._slot_trace.pop(slot, None)
        self._slot_seed.pop(slot, None)
        self._fork_n.pop(slot, None)

    def take_preempted(self) -> list:
        """Slots preempted since the last drain (the scheduler calls
        this after `step_burst` and after its admission loop, re-queues
        the victims' requests, and re-prefills them when room returns)."""
        out, self._preempted = self._preempted, []
        return out

    # ---------------------------------------------------------- admission
    def admit(self, prompt: Sequence[int], *, seed: int = 0,
              max_positions: Optional[int] = None,
              trace_id: Optional[str] = None,
              sampling: Optional[Tuple] = None) -> int:
        """Prefill `prompt` into a free slot + blocks; the slot id.

        `max_positions` is the request's decode-position budget
        (burst-rounded max_new_tokens from the scheduler): no longer a
        reservation, just the growth CAP (`_grow_tables` refuses past
        it) and the whole-pool feasibility check. None caps at the
        per-slot capacity.

        With `EngineConfig.prefix_cache` the prompt first walks the
        radix tree: matched blocks join this slot's page table
        refcounted (their prefill is SKIPPED), only the suffix runs
        through `_prefix_prefill` at canonical positions, and the
        prompt's own full blocks are inserted for future admissions.

        With `EngineConfig.prefill_chunk`, an uncached suffix longer
        than one chunk makes this a CHUNK admission: bookkeeping only
        here (the slot stays inactive, holding just the shared prefix
        blocks), and the caller drives `prefill_step(slot)` once per
        tick until it returns True — Sarathi-style prefill/decode
        interleaving (the scheduler's chunk pump).

        `sampling` = per-request (temperature, top_k, top_p) overrides,
        None fields defaulting to the config
        (EngineConfig.per_slot_sampling).
        """
        p = len(prompt)
        if p == 0:
            raise ValueError("prompt must contain at least one token")
        bs = self.config.block_size
        shared: list = []
        matched = 0
        if self.radix is not None:
            shared, matched = self.radix.match(prompt)
            self.last_prefix_hit = matched
        chunk = self.config.prefill_chunk
        chunked = bool(chunk) and (p - matched) > chunk
        try:
            w = self.bucket_for(min(p - matched, chunk) if chunked
                                else p - matched)
        except ValueError:
            self.blocks.free(shared)
            raise
        # the slot's context END: the plain path starts at length w
        # (left-padding counts as positions), the prefix path at the
        # true p — but its prefill pad rows touch up to matched + w
        if max_positions is None:
            max_positions = self.max_context - (
                w if self.radix is None else max(matched + w, p)
            )
        if self.radix is None:
            end = w + max_positions
        else:
            end = max(matched + w, p + max_positions)
        if end > self.max_context:
            self.blocks.free(shared)
            raise ValueError(
                f"prompt {p} (prefill span {matched + w}) + max_positions "
                f"{max_positions} exceeds the per-slot capacity "
                f"{self.max_context} (= max_blocks_per_slot * block_size)"
            )
        if self._blocks_for(end) > self.blocks.num_blocks - 1:
            self.blocks.free(shared)
            raise ValueError(
                f"prompt {p} + max_positions {max_positions} outgrows "
                f"the whole pool ({self.blocks.num_blocks - 1} blocks)"
            )
        slot = self.allocator.alloc()
        if slot is None:
            self.blocks.free(shared)
            raise RuntimeError("no free slot — scheduler must gate admits")
        try:
            self._set_sampling(slot, sampling)
        except ValueError:
            self.allocator.free(slot)
            self.blocks.free(shared)
            raise
        n_shared = len(shared)
        if chunked:
            # chunk admission: bookkeeping only. The shared prefix
            # joins the table refcounted; every uncached token —
            # including the first chunk — lands through prefill_step,
            # which grows blocks like decode does (_acquire_decode).
            # The slot stays INACTIVE until the final chunk: decode
            # bursts pad it (their garbage write at _len[slot] is
            # overwritten by the next chunk, or lands in the garbage
            # block while unallocated) and preemption never picks it.
            self._pt[slot, :] = 0
            self._pt[slot, :n_shared] = shared
            self._nblk[slot] = n_shared
            self._budget[slot] = min(
                max(self._blocks_for(end), n_shared),
                self.max_blocks_per_slot,
            )
            self._seq[slot] = self._admit_seq
            self._admit_seq += 1
            self._len[slot] = matched
            self._attn[slot] = 0
            self._pending_prompt[slot] = {
                "prompt": [int(t) for t in prompt], "done": matched,
            }
            tr = self.tracer
            if tr is not None and tr.enabled:
                tid = trace_id or f"slot{slot}"
                self._slot_trace[slot] = tid
                tr.instant("chunk_admit", trace_id=tid,
                           pid=self.replica, tid=SLOT_LANE_BASE + slot,
                           prompt_len=p, prefix_hit=matched,
                           chunk=chunk, slot=slot)
            self._keys = self._keys.at[slot].set(jax.random.PRNGKey(seed))
            self._slot_seed[slot] = (seed,)
            self._fork_n.pop(slot, None)
            return slot
        n_table = self._blocks_for(matched + w)
        try:
            ids = self._acquire_admit(n_table - n_shared)
        except RuntimeError:
            self.allocator.free(slot)
            self.blocks.free(shared)
            raise
        self._pt[slot, :] = 0
        self._pt[slot, :n_shared] = shared
        self._pt[slot, n_shared:n_table] = ids
        self._nblk[slot] = n_table
        self._budget[slot] = min(
            max(self._blocks_for(end), n_table), self.max_blocks_per_slot
        )
        self._seq[slot] = self._admit_seq
        self._admit_seq += 1
        tr = self.tracer
        if tr is not None and tr.enabled:
            tid = trace_id or f"slot{slot}"
            self._slot_trace[slot] = tid
            span = tr.span("prefill", trace_id=tid, pid=self.replica,
                           tid=SLOT_LANE_BASE + slot, bucket=w,
                           prompt_len=p, slot=slot, blocks=n_table,
                           prefix_hit=matched)
            ann = jax.profiler.TraceAnnotation(f"serve:prefill:{tid}")
        else:
            span = ann = _NULL
        if self.radix is None:
            # plain path, unchanged since PR 3: LEFT-padded scratch
            # prefill + block scatter
            self._len[slot] = w
            self._attn[slot] = w - p
            padded = np.full((1, w), self.config.pad_id, np.int32)
            padded[0, w - p:] = np.asarray(prompt, np.int32)
            with span, ann:
                self._cache, self._last_logits = self._prefill_jit(
                    self.params, self._cache, self._last_logits,
                    jnp.asarray(padded), jnp.int32(w - p),
                    jnp.asarray(ids, jnp.int32), jnp.int32(slot),
                )
                _await_dispatch(self._cache, self._last_logits)
        else:
            # prefix path: canonical positions, RIGHT-padded suffix
            # appended at `matched` through the page table; the hit's
            # [0, matched) prefill chunks are never recomputed
            sl = p - matched
            self._len[slot] = matched + sl
            self._attn[slot] = 0
            padded = np.full((1, w), self.config.pad_id, np.int32)
            padded[0, :sl] = np.asarray(prompt[matched:], np.int32)
            with span, ann:
                self._cache, self._last_logits = self._prefix_jit(
                    self.params, self._cache, self._last_logits,
                    jnp.asarray(padded), jnp.int32(matched),
                    jnp.int32(sl),
                    jnp.asarray(self._pt[slot:slot + 1]),
                    jnp.int32(slot),
                )
                _await_dispatch(self._cache, self._last_logits)
            # publish this prompt's own full blocks for future hits
            # (already-cached chunks keep their existing node)
            n_full = p // bs
            if n_full:
                self.radix.insert(
                    prompt, [int(b) for b in self._pt[slot, :n_full]]
                )
        # keyed by the REQUEST's seed alone, as in SlotEngine: placement
        # must stay invisible to the sample stream
        self._keys = self._keys.at[slot].set(jax.random.PRNGKey(seed))
        self._slot_seed[slot] = (seed,)
        self._fork_n.pop(slot, None)
        self._active[slot] = True
        if self.drafter is not None:
            # readmission after preemption passes prompt + salvaged
            # tokens here, so the drafter's context is always the
            # slot's true prefix — it never needs to survive a preempt
            self.drafter.begin(slot, [int(t) for t in prompt])
        return slot

    # ------------------------------------------------- chunked prefill
    def is_prefilling(self, slot: int) -> bool:
        """True while a chunk-admitted slot still has prompt chunks to
        run (the scheduler's chunk pump drives prefill_step until this
        flips)."""
        return slot in self._pending_prompt

    def prefill_step(self, slot: int) -> bool:
        """Run ONE prefill chunk for a chunk-admitted slot; True when
        the prompt is fully prefilled (the slot just went active).

        Each chunk is a `_prefix_prefill` dispatch — the suffix-append
        program admission already compiles, at the chunk's bucket width
        — placed at slot-local positions [done, done+take) through the
        page table. Blocks grow per chunk via `_acquire_decode` (free
        list → prefix eviction → LIFO preemption of ACTIVE slots; this
        inactive slot is never its own victim), and only for the REAL
        tokens: a chunk's pad-tail rows scatter into the garbage block
        past the table, so no block is ever held for padding. The final
        chunk publishes the prompt's full blocks to the radix cache,
        seeds the drafter, and activates the slot — exactly the state a
        whole-prompt admission leaves behind, so everything downstream
        (decode, preemption, release) is chunk-blind.

        Raises RuntimeError when the pool cannot cover a chunk even
        after preempting every active slot — the scheduler treats that
        like any admission failure (releases and requeues)."""
        st = self._pending_prompt[slot]
        prompt = st["prompt"]
        p = len(prompt)
        done = st["done"]
        take = min(p - done, self.config.prefill_chunk)
        w = self.bucket_for(take)
        need = self._blocks_for(done + take)
        grow = need - int(self._nblk[slot])
        if grow > 0:
            if need > self.max_blocks_per_slot:
                raise RuntimeError(
                    f"slot {slot} prompt chunk needs {need} blocks, "
                    f"past the per-slot capacity "
                    f"{self.max_blocks_per_slot}"
                )
            ids = self._acquire_decode(grow, protect=slot)
            self._pt[slot, self._nblk[slot]:need] = ids
            self._nblk[slot] = need
        padded = np.full((1, w), self.config.pad_id, np.int32)
        padded[0, :take] = np.asarray(prompt[done:done + take], np.int32)
        tr = self.tracer
        if tr is not None and tr.enabled:
            tid = self._slot_trace.get(slot, f"slot{slot}")
            span = tr.span("prefill_chunk", trace_id=tid,
                           pid=self.replica, tid=SLOT_LANE_BASE + slot,
                           bucket=w, pos0=done, take=take, slot=slot)
            ann = jax.profiler.TraceAnnotation(
                f"serve:prefill_chunk:{tid}"
            )
        else:
            span = ann = _NULL
        with span, ann:
            self._cache, self._last_logits = self._prefix_jit(
                self.params, self._cache, self._last_logits,
                jnp.asarray(padded), jnp.int32(done), jnp.int32(take),
                jnp.asarray(self._pt[slot:slot + 1]), jnp.int32(slot),
            )
            _await_dispatch(self._cache, self._last_logits)
        done += take
        st["done"] = done
        self._len[slot] = done
        if done < p:
            return False
        # final chunk: the slot now looks exactly like a whole-prompt
        # prefix admission — publish, seed the drafter, go active
        del self._pending_prompt[slot]
        floor = self._blocks_for(p)
        self._nblk[slot] = rewind_block_tail(
            self.blocks, self._pt[slot], int(self._nblk[slot]), floor
        )
        n_full = p // self.config.block_size
        if n_full:
            self.radix.insert(
                prompt, [int(b) for b in self._pt[slot, :n_full]]
            )
        if self.drafter is not None:
            self.drafter.begin(slot, [int(t) for t in prompt])
        self._active[slot] = True
        return True

    def fork(self, slot: int, *, seed: Optional[int] = None,
             trace_id: Optional[str] = None) -> int:
        """Clone a running request into a new slot WITHOUT copying its
        context: the child references every parent block (refcounted)
        and carries the same pending logits under a fresh PRNG chain —
        n>1 parallel sampling per prompt for the price of the tail
        blocks the siblings eventually split via copy-on-write.

        Child keys are REPLAYABLE: with no explicit seed the child's
        chain is folded from the parent's seed path plus this fork's
        ordinal — a pure function of (request seed, fork order), so
        siblings diverge by construction AND a replay reproduces each
        sibling's exact stream whatever slot the allocator hands out
        and however many decode steps ran before the fork (the old
        fold-from-current-key default was deterministic in-process but
        changed with both). An explicit `seed=` starts a fresh chain —
        the per-request knob the front door's n>1 sampling rides. A
        slot with no recorded seed path (direct `_keys` manipulation in
        tests) falls back to folding the parent's current key."""
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not active")
        child = self.allocator.alloc()
        if child is None:
            raise RuntimeError("no free slot — gate fork like an admit")
        n = int(self._nblk[slot])
        self.blocks.ref([int(b) for b in self._pt[slot, :n]])
        self._pt[child, :] = self._pt[slot, :]
        self._len[child] = self._len[slot]
        self._attn[child] = self._attn[slot]
        self._nblk[child] = n
        self._budget[child] = self._budget[slot]
        # siblings sample under the parent's params (they diverge by
        # PRNG chain, not by distribution)
        self._temp[child] = self._temp[slot]
        self._topk[child] = self._topk[slot]
        self._topp[child] = self._topp[slot]
        self._seq[child] = self._admit_seq
        self._admit_seq += 1
        if seed is not None:
            key = jax.random.PRNGKey(seed)
            self._slot_seed[child] = (seed,)
        else:
            path = self._slot_seed.get(slot)
            if path is not None:
                self._fork_n[slot] = self._fork_n.get(slot, 0) + 1
                path = path + (self._fork_n[slot],)
                key = jax.random.PRNGKey(path[0])
                for ordinal in path[1:]:
                    key = jax.random.fold_in(key, ordinal)
                self._slot_seed[child] = path
            else:
                key = jax.random.fold_in(self._keys[slot], child)
        self._last_logits, self._keys = self._fork_jit(
            self._last_logits, self._keys, jnp.int32(slot),
            jnp.int32(child), key,
        )
        _await_dispatch(self._last_logits, self._keys)
        self._active[child] = True
        if self.drafter is not None:
            self.drafter.begin(child, self.drafter.snapshot(slot))
        if trace_id is not None:
            self._slot_trace[child] = trace_id
        return child

    # ------------------------------------------------------------- decode
    def _grow_tables(self, k: int) -> int:
        """Allocate the blocks the next k decode positions need, per
        active slot oldest-first (growth may preempt — LIFO victims must
        still be ungrown, not half-grown). Stepping a slot past its
        admit-time `max_positions` budget raises BEFORE touching the
        allocator (the analogue of SlotEngine's positions-exhausted
        guard; the scheduler's burst-rounded max_positions never trips
        it). Returns the number of blocks grown (the decode-burst
        span's `blocks_grown` attribute)."""
        total_grown = 0
        order = sorted(np.flatnonzero(self._active),
                       key=lambda s: self._seq[s])
        for slot in order:
            if not self._active[slot]:
                continue  # preempted by an older slot's growth
            need = self._blocks_for(int(self._len[slot]) + k)
            grow = need - int(self._nblk[slot])
            if grow <= 0:
                continue
            if need > int(self._budget[slot]) \
                    or need > self.max_blocks_per_slot:
                raise RuntimeError(
                    f"slot {slot} stepped past its admit-time block "
                    f"budget (needs {need} blocks, budget "
                    f"{int(self._budget[slot])}) — admit with a larger "
                    f"max_positions"
                )
            ids = self._acquire_decode(grow, protect=int(slot))
            self._pt[slot, self._nblk[slot]:need] = ids
            self._nblk[slot] = need
            total_grown += grow
        return total_grown

    def _cow_split(self, k: int) -> int:
        """Copy-on-write pass before a burst: any EXISTING table block
        the next k positions will write into (fork siblings' shared
        tail) is first copied into a private block — a shared block is
        never mutated, so no sibling or cached prefix ever sees another
        request's tokens. Returns the number of splits (decode-burst
        span attribute)."""
        splits = 0
        bs = self.config.block_size
        for slot in sorted(np.flatnonzero(self._active),
                           key=lambda s: self._seq[s]):
            if not self._active[slot]:
                continue
            length = int(self._len[slot])
            first = length // bs
            last = min((length + k - 1) // bs, int(self._nblk[slot]) - 1)
            for idx in range(first, last + 1):
                b = int(self._pt[slot, idx])
                if self.blocks.refcount(b) <= 1:
                    continue
                assert b != GARBAGE_BLOCK, \
                    "garbage block can never be shared"
                (new,) = self._acquire_decode(1, protect=int(slot))
                # `protect` excludes this slot from the victim list, so
                # the acquire can never have preempted it
                assert self._active[slot], "protected slot was preempted"
                self._cache = self._cow_jit(
                    self._cache, jnp.int32(b), jnp.int32(new)
                )
                _await_dispatch(self._cache)
                self.blocks.free([b])     # drop this slot's ref
                self._pt[slot, idx] = new
                splits += 1
        return splits

    def step_burst(self) -> np.ndarray:
        """One dispatch of `decode_burst` steps; tokens (K, max_slots).
        Per-slot lengths advance by K for active slots; free slots emit
        pad_id and write only the garbage block. Growth / CoW happen
        host-side first and may PREEMPT young slots under pressure —
        preempted slots drop out of this burst (their rows are pads) and
        surface via `take_preempted()`."""
        k = self.config.decode_burst
        grown = self._grow_tables(k)
        splits = self._cow_split(k)
        tr = self.tracer
        if tr is not None and tr.enabled:
            ids = self._dispatch_ids()
            # sampled_only: same head-sampling gate as SlotEngine's
            # burst span — no trace_id, so it rides only while a
            # sampled request is flowing
            span = tr.span("decode_burst", pid=self.replica,
                           tid=ENGINE_LANE, burst=k, active=len(ids),
                           blocks_grown=grown, cow_splits=splits,
                           blocks_free=self.blocks.num_free,
                           sampled_only=True)
            ann = jax.profiler.TraceAnnotation(
                "serve:decode[" + ",".join(ids) + "]"
            )
        else:
            span = ann = _NULL
        with span, ann:
            (self._cache, self._last_logits, toks,
             self._keys, finite) = self._decode_jit(
                self.params, self._cache, self._last_logits,
                jnp.asarray(self._attn), jnp.asarray(self._active),
                self._keys, jnp.asarray(self._pt), jnp.asarray(self._len),
                self._sampling_args(),
            )
            _await_dispatch(self._cache, self._last_logits, self._keys)
            self._len[self._active] += k
            toks, finite = jax.device_get((toks, finite))
        self.burst_seq += 1
        self.last_burst_active = int(np.count_nonzero(self._active))
        self.last_finite = np.asarray(finite)
        toks = np.asarray(toks)
        if self.drafter is not None:
            # plain-burst tokens grow the drafter's context too — a tick
            # without proposals must not blind the next one
            for slot in np.flatnonzero(self._active):
                self.drafter.extend(int(slot), toks[:, slot].tolist())
        return toks

    # ------------------------------------------------- speculative decoding
    def propose_drafts(self):
        """Ask the drafter for every active slot's next-token proposals
        (host-pure, microseconds). Returns (drafts (max_slots, spec_k)
        int32, draft_lens (max_slots,) int32, any_drafted bool) — the
        scheduler dispatches `step_verify` when any slot drafted and
        falls back to `step_burst` otherwise (both greedy-exact, so the
        choice is invisible in the token stream)."""
        if self.drafter is None:
            raise RuntimeError("propose_drafts needs spec_decode=True")
        k = self.config.spec_k
        drafts = np.zeros((self.config.max_slots, k), np.int32)
        lens = np.zeros((self.config.max_slots,), np.int32)
        for slot in np.flatnonzero(self._active):
            d = self.drafter.propose(int(slot), k)
            if d:
                drafts[slot, :len(d)] = d
                lens[slot] = len(d)
        return drafts, lens, bool(lens.any())

    def step_verify(self, drafts: np.ndarray,
                    draft_lens: np.ndarray) -> tuple:
        """One verify dispatch over a drafted window (`_verify` for the
        program; this is its host half). Returns (tokens, counts,
        finite): tokens (spec_k+1, max_slots) row-major like a burst,
        counts (max_slots,) how many leading rows are REAL for each
        slot (accepted + 1 correction; 0 for inactive slots), finite
        (spec_k+1, max_slots) per-token flags.

        Per-slot lengths advance by counts — a slot whose whole draft
        was rejected still nets one real token (the correction IS the
        plain greedy token), so a verify dispatch never loses ground
        to a burst. Growth covers the worst case (spec_k + 1
        positions) up front and the rejected tail's surplus blocks are
        returned to the pool after the dispatch — speculation holds
        blocks only for tokens it actually kept."""
        if self.drafter is None:
            raise RuntimeError("step_verify needs spec_decode=True")
        k = int(drafts.shape[1])
        nblk_before = self._nblk.copy()
        grown = self._grow_tables(k + 1)
        splits = self._cow_split(k + 1)
        tr = self.tracer
        if tr is not None and tr.enabled:
            ids = self._dispatch_ids()
            span = tr.span("verify", pid=self.replica,
                           tid=ENGINE_LANE, k=k, active=len(ids),
                           drafted=int(draft_lens.sum()),
                           blocks_grown=grown, cow_splits=splits,
                           sampled_only=True)
            ann = jax.profiler.TraceAnnotation(
                "serve:verify[" + ",".join(ids) + "]"
            )
        else:
            span = ann = _NULL
        with span, ann:
            (self._cache, self._last_logits, toks,
             accepted, finite) = self._verify_jit(
                self.params, self._cache, self._last_logits,
                jnp.asarray(self._attn), jnp.asarray(self._active),
                jnp.asarray(drafts), jnp.asarray(draft_lens),
                jnp.asarray(self._pt), jnp.asarray(self._len),
            )
            _await_dispatch(self._cache, self._last_logits)
            toks, accepted, finite = jax.device_get(
                (toks, accepted, finite)
            )
        accepted = np.asarray(accepted)
        counts = np.where(self._active, accepted + 1, 0).astype(np.int64)
        self._len[self._active] += counts[self._active].astype(np.int32)
        # rollback, block half: surplus blocks grown for the rejected
        # tail (provably this dispatch's own fresh allocations — the
        # floor never dips below the pre-grow table) go back to the pool
        for slot in np.flatnonzero(self._active):
            floor = max(self._blocks_for(int(self._len[slot])),
                        int(nblk_before[slot]))
            self._nblk[slot] = rewind_block_tail(
                self.blocks, self._pt[slot], int(self._nblk[slot]), floor
            )
        self.spec_drafted_tokens += int(draft_lens[self._active].sum())
        self.spec_accepted_tokens += int(accepted[self._active].sum())
        self.spec_dispatches += 1
        self.burst_seq += 1
        self.last_burst_active = int(np.count_nonzero(self._active))
        toks = np.asarray(toks).T          # (k+1, max_slots) row-major
        finite = np.asarray(finite).T
        self.last_finite = finite
        if self.drafter is not None:
            for slot in np.flatnonzero(self._active):
                n = int(counts[slot])
                self.drafter.extend(int(slot), toks[:n, slot].tolist())
        return toks, counts, finite

    def context_len(self, slot: int) -> int:
        """The slot's current context length (prompt span + decoded
        tokens) — can exceed the model's max_len, the paged headline."""
        return int(self._len[slot])

    def fits_prompt(self, prompt_len: int) -> bool:
        """Chunked mode unbinds servability from the bucket table: any
        prompt whose tokens + one decode position fit the per-slot
        capacity and the pool can be chunk-prefilled."""
        if self.config.prefill_chunk:
            return (prompt_len + 1 <= self.max_context
                    and self._blocks_for(prompt_len + 1)
                    <= self.blocks.num_blocks - 1)
        return super().fits_prompt(prompt_len)

    def poison_slot(self, slot: int) -> None:
        """NaN one slot's pending sampling input (serve/faults.py) —
        identical contract to SlotEngine.poison_slot."""
        self._last_logits = self._last_logits.at[slot].set(jnp.nan)

    def compile_stats(self) -> dict:
        """The two PR-3 programs plus the PR-6 admission paths plus the
        speculative verify program — all five counters must stay flat
        under churn (prefix hits, CoW splits, preempt/readmit, verify
        dispatches included; conftest `compile_guard`)."""
        return {
            "prefill_compiles": self._prefill_jit._cache_size(),
            "decode_compiles": self._decode_jit._cache_size(),
            "prefix_prefill_compiles": self._prefix_jit._cache_size(),
            "cow_compiles": self._cow_jit._cache_size(),
            "verify_compiles": self._verify_jit._cache_size(),
        }

    def _clear_slot(self, slot: int) -> None:
        self._pending_prompt.pop(slot, None)
        n = int(self._nblk[slot])
        if n:
            self.blocks.free([int(b) for b in self._pt[slot, :n]])
        if self.drafter is not None:
            self.drafter.end(slot)
        self.allocator.free(slot)
        self._pt[slot, :] = 0
        self._nblk[slot] = 0
        self._budget[slot] = 0
        self._len[slot] = 0
        self._attn[slot] = 0
        self._active[slot] = False

    def release(self, slot: int) -> None:
        """Free the slot and DEREF its blocks: sole-owned blocks return
        to the pool, blocks shared with the prefix cache or fork
        siblings stay for their other holders. The page-table row is
        pointed back at the garbage block so the batched decode keeps
        static shapes; stale K/V in freed blocks is invisible to the
        next occupant (masked to its own written positions — pinned in
        tests/test_kv_pages.py)."""
        self._clear_slot(slot)
        self._slot_trace.pop(slot, None)
        self._slot_seed.pop(slot, None)
        self._fork_n.pop(slot, None)

    def reset_epoch(self) -> None:
        """Interface parity with SlotEngine (the router calls this in
        warmup() and replica restart()): there is no pool clock to
        rewind — every release already returned its pages — so with all
        slots free this is a no-op (the prefix cache deliberately
        SURVIVES: warm prefixes are the point); with active slots it
        raises, same contract as the slot pool."""
        if self.allocator.num_used:
            raise RuntimeError("reset_epoch with active slots")
