"""Per-tenant admission control for the serving front door.

The `Request.tenant` label already rides every seam (scheduler ->
router -> RPC -> worker, trace sampling, SLO attribution); this module
is the knob that makes it mean something at the door: each tenant gets
a token-bucket rate limit plus a concurrent-streams cap, and a request
that exceeds either is refused with a TYPED reason before it touches
the router — a 429 at the door instead of a queue slot a paying tenant
needed.

Token bucket over a leaky counter because burst tolerance is the
point: a tenant allowed 10 rps should be able to send its 10 requests
back-to-back at the top of the second (burst), not be clocked at one
per 100 ms. The bucket refills continuously at `rate_rps` up to
`burst`; each admission spends one token.

Deliberately host-pure and clock-injected (same FakeClock discipline
as the scheduler/router): admission decisions replay deterministically
in tests, and the front door passes its real clock in production.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """One tenant's admission envelope. `rate_rps <= 0` disables the
    rate check (unlimited); `max_concurrent <= 0` disables the
    concurrency check. `burst` defaults to one second of rate (min 1)
    so a bare rate is usable without tuning."""

    rate_rps: float = 0.0
    burst: Optional[int] = None
    max_concurrent: int = 0

    def bucket_size(self) -> float:
        if self.burst is not None:
            return float(max(1, self.burst))
        return float(max(1.0, self.rate_rps))


class AdmissionController:
    """Thread-safe per-tenant gate: `try_acquire` at intake,
    `release` when the stream ends (any terminal — end frame, shed,
    connection drop). Unknown tenants (and `tenant=None`) fall under
    `default`; `TenantPolicy()` admits everything, so a front door
    built with no policies behaves exactly like one with no admission
    layer at all."""

    def __init__(self, policies: Optional[Dict[str, TenantPolicy]] = None,
                 *, default: TenantPolicy = TenantPolicy(),
                 clock=None, vtc=None,
                 fair_max_inflight: int = 0) -> None:
        self.policies = dict(policies or {})
        self.default = default
        self._clock = clock
        # optional serve/fairshare.py VirtualTokenCounter + pressure
        # threshold: with BOTH set, once total inflight reaches
        # `fair_max_inflight` the door refuses the MOST-OVER-SERVED
        # tenant's requests first (typed reason "fairness") — the VTC
        # paper's admission half. Static rate/concurrency envelopes
        # can't do this: they don't know who already ate the capacity.
        self.vtc = vtc
        self.fair_max_inflight = fair_max_inflight
        self._lock = threading.Lock()
        self._tokens: Dict[str, float] = {}     # bucket fill per tenant
        self._refill_at: Dict[str, float] = {}  # last refill timestamp
        self._inflight: Dict[str, int] = {}
        # cumulative per-reason refusal counts (the front door exports
        # these; kept here so a headless controller is still auditable)
        self.refused: Dict[str, int] = {
            "rate": 0, "concurrency": 0, "fairness": 0,
        }

    def _now(self) -> float:
        return self._clock.now() if self._clock is not None \
            else time.monotonic()

    def policy_for(self, tenant: Optional[str]) -> TenantPolicy:
        if tenant is not None and tenant in self.policies:
            return self.policies[tenant]
        return self.default

    def _fairness_refuses(self, tenant: Optional[str]) -> bool:
        """Under pressure (total inflight >= fair_max_inflight), refuse
        the requester iff it is the MOST-OVER-SERVED tenant among those
        competing (tenants currently inflight, plus itself). Needs at
        least two competing tenants: with one there is no fairness
        question, only capacity — the rate/concurrency envelopes' job.
        Caller holds the lock."""
        if self.vtc is None or self.fair_max_inflight <= 0:
            return False
        if sum(self._inflight.values()) < self.fair_max_inflight:
            return False
        competing = {k or None for k, n in self._inflight.items()
                     if n > 0}
        competing.add(tenant)
        if len(competing) < 2:
            return False
        worst = self.vtc.most_over_served(competing)
        return (worst or "") == (tenant or "")

    def try_acquire(self, tenant: Optional[str]
                    ) -> Tuple[bool, Optional[str]]:
        """(admitted, refusal_reason). Reasons: "fairness" (the
        most-over-served tenant under pressure — see
        `_fairness_refuses`), "rate" (bucket empty) or "concurrency"
        (cap reached). Checks concurrency FIRST, then fairness, so a
        refused tenant does not also burn a rate token for a request
        that was never going to run."""
        pol = self.policy_for(tenant)
        key = tenant or ""
        with self._lock:
            if (pol.max_concurrent > 0
                    and self._inflight.get(key, 0) >= pol.max_concurrent):
                self.refused["concurrency"] += 1
                return False, "concurrency"
            if self._fairness_refuses(tenant):
                self.refused["fairness"] += 1
                return False, "fairness"
            if pol.rate_rps > 0:
                now = self._now()
                size = pol.bucket_size()
                fill = self._tokens.get(key, size)
                last = self._refill_at.get(key, now)
                fill = min(size, fill + (now - last) * pol.rate_rps)
                self._refill_at[key] = now
                if fill < 1.0:
                    self._tokens[key] = fill
                    self.refused["rate"] += 1
                    return False, "rate"
                self._tokens[key] = fill - 1.0
            self._inflight[key] = self._inflight.get(key, 0) + 1
            if self.vtc is not None:
                # register at the current service floor so the first
                # fairness comparison sees this tenant at all
                self.vtc.touch(tenant)
            return True, None

    def release(self, tenant: Optional[str]) -> None:
        """One admitted stream ended. Idempotence is the CALLER's job
        (release once per acquire); the floor-at-zero here only keeps a
        bookkeeping bug from turning into a negative cap that admits
        unboundedly."""
        key = tenant or ""
        with self._lock:
            n = self._inflight.get(key, 0)
            if n > 0:
                self._inflight[key] = n - 1

    def inflight(self, tenant: Optional[str]) -> int:
        with self._lock:
            return self._inflight.get(tenant or "", 0)
