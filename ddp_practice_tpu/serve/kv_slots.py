"""Slot-based KV-cache pool: the memory layer of continuous batching.

The one-shot generator (inference.py) allocates a fresh KV cache per
`gen()` call and throws it away — fine for a CLI, fatal for serving,
where a new cache per request means a new jit trace per batch
composition. Here the cache is a fixed **pool**: the flax "cache"
collection of a decode-mode model, allocated ONCE at
`(max_slots, max_len)`, where the batch dimension of every cache leaf is
reinterpreted as a slot index. Requests are admitted into free slots and
released on EOS/length/deadline; shapes never change, so the engine's two
jitted programs (serve/engine.py) compile once and serve arbitrary
request churn.

Alignment invariant (what makes a SHARED write cursor work): the model's
cache keeps one scalar `cache_index` per block — all slots write at the
same position every step. Continuous batching needs per-slot histories,
which this layer gets by LEFT-ALIGNMENT, the same trick as
`pad_left_prompts`: a request admitted while the pool cursor is `cur`
has its prompt prefilled at positions `[cur - w, cur)` (w = padded
bucket width) in a batch-1 scratch cache, whose rows are then scattered
into the pool at the slot index. Its last prompt token lands at
`cur - 1` — exactly where every running request's latest token sits — and
`attn_start = cur - prompt_len` masks everything earlier. RoPE positions
are relative, so the uniform shift is invisible (models/lm.py requires
pos_emb="rope" for attn_start).

The cost of the shared cursor is that pool POSITIONS are a global
resource: every decode step consumes one position for all slots, the
pool drains in `max_len - max_bucket` steps between epoch rewinds
(engine.reset_epoch via make_room), decode attention pays for the whole
`[0, max_len)` span every step, and no request can ever span more than
`max_len` positions. The PAGED layout (kv_pages.py + engine.PagedEngine)
removes all four costs with per-slot block page tables — this module
stays as the simpler layout and the equivalence oracle
(tests/test_serve_equivalence.py drives one trace through both). Stale
K/V from a previous occupant is never visible: `write_slot` overwrites
the slot's ENTIRE row (the scratch cache is zeros outside the prompt
window), and attention only reads `[attn_start, cur]`.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
from jax import lax


def set_cursor(cache: Any, value) -> Any:
    """Return `cache` with every scalar write-cursor leaf set to `value`.

    The decode cache's only scalar leaves are the per-block `cache_index`
    cursors (and `pos_index` for learned positions), so ndim==0 is the
    cursor predicate. `value` may be traced (the scratch prefill sets it
    to a dynamic start inside jit).
    """
    return jax.tree.map(
        lambda l: jnp.asarray(value, l.dtype) if l.ndim == 0 else l, cache
    )


def read_cursor(cache: Any) -> jnp.ndarray:
    """The shared write cursor (any scalar leaf — they advance in lockstep)."""
    for leaf in jax.tree.leaves(cache):
        if leaf.ndim == 0:
            return leaf
    raise ValueError("cache has no scalar cursor leaf — not a decode cache")


def write_slot(pool: Any, scratch: Any, slot) -> Any:
    """Scatter a batch-1 scratch cache into `pool` at row `slot`.

    Non-scalar leaves are `(slots, ...)` vs `(1, ...)` — a
    dynamic_update_slice on the batch axis (slot may be traced). Scalar
    cursor leaves keep the POOL's value: the scratch prefill is
    constructed to end exactly at the pool cursor (engine.admit), so the
    pool's clock is untouched by admissions.
    """

    def per_leaf(p, s):
        if p.ndim == 0:
            return p
        return lax.dynamic_update_slice(
            p, s.astype(p.dtype), (slot,) + (0,) * (p.ndim - 1)
        )

    return jax.tree.map(per_leaf, pool, scratch)


class SlotAllocator:
    """Host-side free-list over the pool's slot indices.

    Pure bookkeeping — no device state. Freed slots go to the BACK of the
    free list so reuse is observable in tests (a released slot is handed
    out again once the older free slots are consumed) and allocation
    order is deterministic.
    """

    def __init__(self, max_slots: int) -> None:
        if max_slots <= 0:
            raise ValueError("max_slots must be positive")
        self.max_slots = max_slots
        self._free: List[int] = list(range(max_slots))
        self._used: set = set()

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        slot = self._free.pop(0)
        self._used.add(slot)
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._used:
            raise ValueError(f"slot {slot} is not allocated")
        self._used.remove(slot)
        self._free.append(slot)

    @property
    def num_used(self) -> int:
        return len(self._used)

    @property
    def num_free(self) -> int:
        return len(self._free)

    def used_slots(self) -> List[int]:
        return sorted(self._used)
