"""Paged KV-cache pool: block-granular memory for continuous batching.

The slot pool (kv_slots.py) shares ONE write cursor: every decode step
consumes a position for all slots, the pool drains in
`max_len - max_bucket` steps between epoch rewinds, and decode attention
scans the whole `[0, max_len)` span every step — BENCHMARKS.md measured
the span cost directly (halving max_len moved continuous/static
throughput 0.54x -> ~1.0x). This module replaces positions-as-a-global-
resource with vLLM-style paging:

- the flax "cache" collection of a decode-mode model is allocated as a
  POOL of fixed-size blocks: every `cached_key`/`cached_value` leaf is
  `(num_blocks, block_size, h*hd)` (same flat minor layout as the slot
  pool — in-place TPU updates, ops/decode_attention.py); an int8 cache
  model (kv_cache_dtype="int8", models/vit.py) additionally pools its
  per-(head, position) fp32 scales as `(num_blocks, h, block_size)`
  leaves — the per-BLOCK scale pages that halve KV bytes/token;
- each slot owns a host-side list of blocks plus a device-side PAGE
  TABLE row (`[max_slots, max_blocks_per_slot]` int32): position `p` of
  a slot lives in pool block `page_table[slot, p // block_size]` at row
  `p % block_size`. Positions are SLOT-LOCAL, starting at 0 — there is
  no shared clock, so nothing drains and nothing rewinds;
- admission scatters the bucketed scratch prefill into freshly allocated
  blocks (`scatter_prompt_blocks`), decode appends at each slot's own
  write position, release returns the slot's blocks to the free list
  individually, and a request's context can outgrow the slot engine's
  `max_len` as long as blocks exist.

Blocks are REFCOUNTED (PR 6): a block may be referenced by several
slots at once (shared prompt prefix, forked sampling siblings) and by
the radix prefix cache below; `free` is a deref and the block returns
to the free list only at refcount zero. Copy-on-write keeps sharing
sound: a slot about to WRITE into a block with refcount > 1 first
copies it into a private block (`copy_block`, serve/engine.py
`_ensure_writable`).

`RadixPrefixCache` is a block-granular radix tree over the pool: each
node is one FULL block of `block_size` prompt tokens at canonical
slot-local positions (node depth i covers positions [i*bs, (i+1)*bs)).
Admission walks the tree with the new prompt (`match`) and re-uses the
matched blocks outright — those prefill chunks are never recomputed —
then inserts its own full prompt blocks (`insert`) so later requests
hit them. The tree holds one reference per cached block; eviction
(`evict`) walks unreferenced LEAF nodes in LRU order, so a block is
never reclaimed while any slot still attends through it
(evict-while-referenced is structurally impossible — pinned in
tests/test_kv_pages.py). Sharing requires canonical positions, so the
prefix-cache admission path right-pads (attn_start 0) instead of the
plain path's left-padding — RoPE makes both layouts equivalent.

Block 0 is the pool's designated GARBAGE block: it is never handed out
by the allocator, never refcounted, never a copy-on-write source or
target, and retired slots' page-table rows point at it, so the batched
decode step can keep scattering for every batch row (static shapes,
zero recompiles) without a freed slot ever touching a live request's
pages. Stale K/V inside a reused block is never visible: a new
occupant's attention is masked to `[attn_start, length]` in its own
slot-local coordinates, and every position it does attend was written by
its own prefill/decode — or by the SAME tokens' prefill under a cache
hit (tests/test_kv_pages.py pins both).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ddp_practice_tpu.inference import make_cache

# pool block index reserved as the write target of retired slots; the
# allocator never hands it out, refcounts it, or copies into it
GARBAGE_BLOCK = 0


class BlockAllocator:
    """Host-side refcounted free-list over the pool's block indices.

    Pure bookkeeping, same idiom as kv_slots.SlotAllocator: freed blocks
    go to the BACK of the free list, so allocation order is deterministic
    and reuse is observable in tests. `alloc(n)` is all-or-nothing —
    a request either gets its blocks or None (the scheduler's admission
    gate turns None into queueing, never a crash).

    Blocks carry a REFCOUNT: `alloc` hands them out at 1, `ref` adds a
    holder (another slot sharing the block, the radix prefix cache),
    `free` drops one — the block returns to the free list only when the
    last holder lets go. A never-shared pool behaves exactly like the
    PR-3 allocator. Block 0 (GARBAGE_BLOCK) is outside the economy
    entirely: alloc never returns it and ref/free refuse it loudly (the
    retired-slot DMA convention must never alias a live/shared block).
    """

    def __init__(self, num_blocks: int) -> None:
        if num_blocks <= 1:
            raise ValueError(
                f"need at least 2 blocks (block {GARBAGE_BLOCK} is the "
                f"garbage block), got {num_blocks}"
            )
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(1, num_blocks))
        self._refs: Dict[int, int] = {}
        # optional refcount-transition hook: called as on_refcount(block,
        # count) after every ref/free. The radix prefix cache subscribes
        # to keep its evictable-blocks counter O(1) — a cached leaf flips
        # between evictable and pinned exactly when its refcount crosses
        # the 1 <-> 2 boundary, which only the allocator can see.
        self.on_refcount = None

    def alloc(self, n: int = 1) -> Optional[List[int]]:
        """n blocks at refcount 1, or None if fewer than n are free
        (all-or-nothing)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        blocks = self._free[:n]
        del self._free[:n]
        for b in blocks:
            assert b != GARBAGE_BLOCK, "garbage block leaked into free list"
            self._refs[b] = 1
        return blocks

    def ref(self, blocks: Sequence[int]) -> None:
        """Add one holder to each block (prefix-cache hit, fork)."""
        for b in blocks:
            if b == GARBAGE_BLOCK:
                raise ValueError(
                    f"block {GARBAGE_BLOCK} is the garbage block — it can "
                    f"never be shared or refcounted"
                )
            if b not in self._refs:
                raise ValueError(f"block {b} is not allocated")
            self._refs[b] += 1
            if self.on_refcount is not None:
                self.on_refcount(b, self._refs[b])

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one holder per block; a block with no holders left
        returns to the BACK of the free list."""
        for b in blocks:
            if b == GARBAGE_BLOCK:
                raise ValueError(
                    f"block {GARBAGE_BLOCK} is the garbage block — retired "
                    f"page-table rows point at it, it is never allocated "
                    f"or freed"
                )
            if b not in self._refs:
                raise ValueError(f"block {b} is not allocated")
            self._refs[b] -= 1
            count = self._refs[b]
            if count == 0:
                del self._refs[b]
                self._free.append(b)
            if self.on_refcount is not None:
                self.on_refcount(b, count)

    def refcount(self, block: int) -> int:
        """Current holder count (0 = free; garbage block reads 0)."""
        return self._refs.get(block, 0)

    @property
    def num_used(self) -> int:
        return len(self._refs)

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_shared(self) -> int:
        """Blocks held by more than one holder — the sharing observable
        behind the `kv_blocks_shared` gauge."""
        return sum(1 for c in self._refs.values() if c > 1)


class _RadixNode:
    """One full block of the radix tree: `tokens` is the block_size-token
    edge label, `block` the pool block holding those positions' K/V."""

    __slots__ = ("tokens", "block", "children", "parent", "last_use")

    def __init__(self, tokens: Tuple[int, ...], block: int, parent) -> None:
        self.tokens = tokens
        self.block = block
        self.children: Dict[Tuple[int, ...], "_RadixNode"] = {}
        self.parent = parent
        self.last_use = 0


class RadixPrefixCache:
    """Block-granular radix tree mapping prompt prefixes to pool blocks.

    Depth-i nodes hold slot-local positions [i*block_size, (i+1)*bs) of
    some previously served prompt; only FULL blocks are cached (a
    partial tail block is private to its request — it would otherwise
    be written by that request's decode while shared). The tree holds
    one allocator reference per node, so cached blocks survive their
    original request's release; `evict` drops LRU leaves whose blocks
    have no other holder, leaf-first, so nothing a slot still attends
    through can ever be reclaimed.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int) -> None:
        self.allocator = allocator
        self.block_size = block_size
        self._root = _RadixNode((), GARBAGE_BLOCK, None)
        self._clock = 0          # LRU tick, bumped per touch
        self._nodes = 0
        self.hit_tokens = 0      # cumulative matched / recomputed token
        self.miss_tokens = 0     # counters (ServeMetrics exports deltas)
        # structural-change counter (insert/evict edges only): the
        # prefix-digest publisher (serve/affinity.py) rebuilds its
        # fingerprint exactly when this moves, so idle heartbeats never
        # re-walk a warm tree
        self.edit_seq = 0
        # O(1) evictable accounting: `_leaf_index` maps block -> its LEAF
        # node (a block appears at most once in the tree — insert only
        # ever refs a freshly allocated, caller-owned block), and
        # `_evictable` is the subset whose allocator refcount is exactly
        # 1 (the tree is the only holder). admit_gate probes evictable()
        # on EVERY blocked admission; before this counter each probe
        # walked the whole tree — linear in a big warm cache. Structural
        # transitions (insert/evict) are maintained here; refcount
        # transitions (a slot attaching to or releasing a cached block)
        # arrive through the allocator's on_refcount hook.
        self._leaf_index: Dict[int, _RadixNode] = {}
        self._evictable: set = set()
        allocator.on_refcount = self._on_refcount

    def __len__(self) -> int:
        return self._nodes

    # ------------------------------------------ evictable bookkeeping
    def _on_refcount(self, block: int, count: int) -> None:
        """Allocator hook: a leaf's block crossed a refcount boundary.
        count == 1 with the tree holding the block means evictable;
        anything else (a slot still attends through it, or the block
        is not a leaf/not cached) means not."""
        if block in self._leaf_index:
            if count == 1:
                self._evictable.add(block)
            else:
                self._evictable.discard(block)

    def _leaf_gained(self, node: "_RadixNode") -> None:
        """`node` just became a leaf (inserted, or its last child was
        evicted): index it and classify its evictability."""
        if node is self._root:
            return
        self._leaf_index[node.block] = node
        if self.allocator.refcount(node.block) == 1:
            self._evictable.add(node.block)

    def _leaf_lost(self, node: "_RadixNode") -> None:
        """`node` is no longer a leaf (gained a child) or no longer in
        the tree (evicted): drop it from the evictable accounting."""
        self._leaf_index.pop(node.block, None)
        self._evictable.discard(node.block)

    def _chunks(self, tokens: Sequence[int]):
        bs = self.block_size
        for i in range(len(tokens) // bs):
            yield tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])

    def _walk(self, tokens: Sequence[int]) -> list:
        """Nodes along the longest cached block-chunk prefix, in order.
        Side-effect free — `match` stamps LRU ticks and takes refs on
        top of this, `peek` deliberately does neither."""
        node = self._root
        out: list = []
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            node = child
            out.append(node)
        return out

    def _clamp_full(self, items: list, tokens: Sequence[int]) -> list:
        """Drop trailing matched items until at least ONE token of
        `tokens` is left to prefill — admission must produce the last
        prompt token's logits, which no cache holds. THE one clamp
        shared by `match` / `peek` / `ref_prefix`: the gate, the
        admission, and the room-making pin must agree on matched
        length or a feasible admission desynchronizes from its gate."""
        while items and len(items) * self.block_size >= len(tokens):
            items.pop()
        return items

    def peek(self, tokens: Sequence[int]) -> int:
        """Read-only longest-cached-prefix length in TOKENS, with
        `match`'s always-leave-one-to-prefill clamp — the admission
        gate's probe: no LRU stamp, no refs, no hit/miss accounting, so
        gating a request never perturbs cache state."""
        clamped = self._clamp_full(self._walk(tokens), tokens)
        return len(clamped) * self.block_size

    def ref_prefix(self, tokens: Sequence[int]) -> List[int]:
        """Temporarily PIN the cached prefix chain of `tokens`: refs
        every matched block (same walk + leave-one-to-prefill clamp as
        `match`, but no LRU stamp and no hit/miss accounting) and
        returns them — the caller MUST `allocator.free()` the list to
        drop the pins. `make_room` uses this to spare the blocked
        request's own prefix while aging out the rest of the cache."""
        blocks = self._clamp_full(
            [n.block for n in self._walk(tokens)], tokens)
        self.allocator.ref(blocks)
        return blocks

    def match(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached prefix of `tokens`: (blocks, matched_tokens).

        Matching is block-granular and always leaves at least ONE prompt
        token uncached — the admission prefill must produce the last
        prompt token's logits, which no cache holds. The caller owns a
        reference on each returned block (`allocator.ref` applied here),
        so a concurrent eviction can never pull a matched block out from
        under the admission that is about to attend through it.
        """
        self._clock += 1
        nodes = self._walk(tokens)
        blocks: List[int] = []
        for node in nodes:
            node.last_use = self._clock
            blocks.append(node.block)
        # never match the WHOLE prompt (`_clamp_full`): at least one
        # token is left to prefill
        blocks = self._clamp_full(blocks, tokens)
        matched = len(blocks) * self.block_size
        self.allocator.ref(blocks)
        self.hit_tokens += matched
        self.miss_tokens += len(tokens) - matched
        return blocks, matched

    def insert(self, tokens: Sequence[int], blocks: Sequence[int]) -> int:
        """Cache `tokens`' full blocks, where `blocks[i]` holds positions
        [i*bs, (i+1)*bs). Chunks already present keep their EXISTING
        block (the caller's duplicate stays private to its slot); new
        nodes take one tree reference on the caller's block. Returns the
        number of nodes added."""
        self._clock += 1
        node = self._root
        added = 0
        for i, chunk in enumerate(self._chunks(tokens)):
            child = node.children.get(chunk)
            if child is None:
                b = int(blocks[i])
                if b == GARBAGE_BLOCK:
                    raise ValueError(
                        "garbage block can never enter the prefix cache"
                    )
                self.allocator.ref([b])
                if not node.children:
                    self._leaf_lost(node)  # interior now, not evictable
                child = _RadixNode(chunk, b, node)
                node.children[chunk] = child
                self._nodes += 1
                added += 1
                self._leaf_gained(child)
            child.last_use = self._clock
            node = child
        if added:
            self.edit_seq += 1
        return added

    def evictable(self) -> int:
        """Blocks `evict` could free right now: leaf-reachable nodes
        whose block has no holder beyond the tree. Admission gates count
        these as available — evicting them is make_room's first move.
        O(1): the counter is maintained incrementally (insert/evict
        structural edges here, slot ref/deref edges via the allocator's
        on_refcount hook) instead of walking the tree per probe."""
        return len(self._evictable)

    def _evictable_walk(self) -> int:
        """The full-tree definition of `evictable()` — O(nodes). Kept as
        the oracle the incremental counter is pinned against
        (tests/test_kv_pages.py randomized op sequence)."""
        return sum(
            1 for n in self._iter_nodes()
            if not n.children and self.allocator.refcount(n.block) == 1
        )

    def _iter_nodes(self):
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    def evict(self, n_blocks: int) -> int:
        """Drop up to `n_blocks` LRU unreferenced LEAF nodes (repeatedly
        — an evicted leaf may expose its parent). Returns blocks freed.
        Nodes whose block another holder (a slot) still references are
        skipped: evict-while-referenced cannot happen by construction.
        """
        freed = 0
        while freed < n_blocks and self._evictable:
            # snapshot this round's victims from the incremental set (an
            # eviction below may expose a parent — it joins the NEXT
            # round, same order the full-walk loop gave)
            victims = sorted(
                (self._leaf_index[b] for b in self._evictable),
                key=lambda n: n.last_use,
            )
            for v in victims:
                if freed >= n_blocks:
                    break
                del v.parent.children[v.tokens]
                self._leaf_lost(v)
                if not v.parent.children:
                    self._leaf_gained(v.parent)
                self.allocator.free([v.block])
                self._nodes -= 1
                freed += 1
        if freed:
            self.edit_seq += 1
        return freed

    def clear(self) -> int:
        """Evict everything evictable (engine reset); returns blocks
        freed. Nodes pinned by live slots stay."""
        return self.evict(self._nodes)


def make_paged_cache(model, num_blocks: int, block_size: int) -> Any:
    """Block-pool cache collection for `model` (decode mode).

    Mirrors the tree structure of `inference.make_cache` — same variable
    names per attention block, so `decode_apply` threads it unchanged —
    but every K/V leaf is `(num_blocks, block_size, h*hd)` instead of
    `(batch, max_len, h*hd)`. An int8 cache model's per-(head, position)
    scale leaves pool the same way: `(1, h, block_size)` becomes
    `(num_blocks, h, block_size)` — per-block scale pages riding the
    same page table as the K/V they dequantize. Scalar leaves (the flat
    layout's write cursors) stay for tree parity; the paged path never
    advances them.
    """
    shapes = jax.eval_shape(lambda: make_cache(model, 1, block_size))
    return jax.tree.map(
        lambda a: jnp.zeros(a.shape, a.dtype) if a.ndim == 0
        else jnp.zeros((num_blocks,) + a.shape[1:], a.dtype),
        shapes,
    )


def _is_scale_leaf(path) -> bool:
    """Scale-pool leaves ((nb, h, bs) — positions on axis 2) vs K/V
    leaves ((nb, bs, h*hd) — positions on axis 1), told apart by the
    cache variable NAME (`cached_key_scale` / `cached_value_scale`,
    models/vit.py) rather than shape heuristics."""
    return any(
        "scale" in str(getattr(k, "key", k)) for k in path
    )


def scatter_prompt_blocks(pool: Any, scratch: Any, block_ids,
                          width: int, block_size: int) -> Any:
    """Scatter a batch-1 contiguous scratch cache into pool blocks.

    `scratch` holds a freshly prefilled prompt at positions `[0, width)`
    of a `(1, width, h*hd)` flat cache; `block_ids` is the
    `(ceil(width / block_size),)` int32 list of destination blocks (may
    be traced — admission happens inside jit). Chunk `i` of the scratch
    lands in pool block `block_ids[i]`; a trailing partial chunk writes
    only its real rows, so whatever the rest of that block held stays —
    and stays invisible, because attention is masked to the slot's own
    positions. int8 scale leaves ((1, h, width) -> (nb, h, block_size))
    chunk along their position axis (2) the same way. Scalar leaves
    keep the POOL's value (no global clock).
    """
    n_chunks = -(-width // block_size)

    def per_leaf(path, p, s):
        if p.ndim == 0:
            return p
        pos_axis = 2 if _is_scale_leaf(path) else 1
        for i in range(n_chunks):
            lo = i * block_size
            rows = min(block_size, width - lo)
            if pos_axis == 1:
                chunk = lax.dynamic_slice(
                    s, (0, lo, 0), (1, rows, s.shape[2])
                ).astype(p.dtype)
                p = lax.dynamic_update_slice(p, chunk, (block_ids[i], 0, 0))
            else:
                chunk = lax.dynamic_slice(
                    s, (0, 0, lo), (1, s.shape[1], rows)
                ).astype(p.dtype)
                p = lax.dynamic_update_slice(p, chunk, (block_ids[i], 0, 0))
        return p

    return jax.tree_util.tree_map_with_path(per_leaf, pool, scratch)


def rewind_block_tail(blocks: BlockAllocator, table_row, nblk: int,
                      floor: int) -> int:
    """Return a page-table row's tail blocks [floor, nblk) to the pool —
    the block half of a length rewind. Speculative verify
    (serve/engine.py step_verify) grows every slot for the worst case
    (`spec_k + 1` positions) before it knows how much of the draft the
    model accepts; after acceptance the rejected tail's positions no
    longer exist, so the blocks grown ONLY for them come straight back.
    The caller picks `floor` so it never dips below the pre-grow table
    (freed blocks are then provably this dispatch's own fresh
    refcount-1 allocations — a shared prefix/fork block can never be in
    the tail). Freed table entries are pointed back at the garbage
    block, keeping the batched dispatch's static shapes safe. Returns
    the new block count (== max(floor, min(nblk, floor)) — i.e. floor,
    or nblk unchanged when there is no tail)."""
    if nblk <= floor:
        return nblk
    tail = [int(b) for b in table_row[floor:nblk]]
    assert GARBAGE_BLOCK not in tail, "garbage block in a live tail"
    blocks.free(tail)
    table_row[floor:nblk] = GARBAGE_BLOCK
    return floor


def copy_block(pool: Any, src, dst) -> Any:
    """Copy one pool block (every non-scalar leaf row `src` -> `dst`) —
    the copy-on-write primitive: a slot about to write into a SHARED
    block first duplicates it into a private one. `src`/`dst` may be
    traced scalars (the engine jits one copy program, reused for every
    split). Copying from/into the garbage block is a caller bug; the
    engine asserts it host-side before dispatch."""

    def per_leaf(p):
        if p.ndim == 0:
            return p
        row = lax.dynamic_slice(
            p, (src,) + (0,) * (p.ndim - 1), (1,) + p.shape[1:]
        )
        return lax.dynamic_update_slice(
            p, row, (dst,) + (0,) * (p.ndim - 1)
        )

    return jax.tree.map(per_leaf, pool)
