"""Paged KV-cache pool: block-granular memory for continuous batching.

The slot pool (kv_slots.py) shares ONE write cursor: every decode step
consumes a position for all slots, the pool drains in
`max_len - max_bucket` steps between epoch rewinds, and decode attention
scans the whole `[0, max_len)` span every step — BENCHMARKS.md measured
the span cost directly (halving max_len moved continuous/static
throughput 0.54x -> ~1.0x). This module replaces positions-as-a-global-
resource with vLLM-style paging:

- the flax "cache" collection of a decode-mode model is allocated as a
  POOL of fixed-size blocks: every `cached_key`/`cached_value` leaf is
  `(num_blocks, block_size, h*hd)` (same flat minor layout as the slot
  pool — in-place TPU updates, ops/decode_attention.py);
- each slot owns a host-side list of blocks plus a device-side PAGE
  TABLE row (`[max_slots, max_blocks_per_slot]` int32): position `p` of
  a slot lives in pool block `page_table[slot, p // block_size]` at row
  `p % block_size`. Positions are SLOT-LOCAL, starting at 0 — there is
  no shared clock, so nothing drains and nothing rewinds;
- admission scatters the bucketed scratch prefill into freshly allocated
  blocks (`scatter_prompt_blocks`), decode appends at each slot's own
  write position, release returns the slot's blocks to the free list
  individually, and a request's context can outgrow the slot engine's
  `max_len` as long as blocks exist.

Block 0 is the pool's designated GARBAGE block: it is never handed out
by the allocator, and retired slots' page-table rows point at it, so the
batched decode step can keep scattering for every batch row (static
shapes, zero recompiles) without a freed slot ever touching a live
request's pages. Stale K/V inside a reused block is never visible: a new
occupant's attention is masked to `[attn_start, length]` in its own
slot-local coordinates, and every position it does attend was written by
its own prefill/decode (tests/test_kv_pages.py pins this).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ddp_practice_tpu.inference import make_cache

# pool block index reserved as the write target of retired slots; the
# allocator never hands it out
GARBAGE_BLOCK = 0


class BlockAllocator:
    """Host-side free-list over the pool's block indices.

    Pure bookkeeping, same idiom as kv_slots.SlotAllocator: freed blocks
    go to the BACK of the free list, so allocation order is deterministic
    and reuse is observable in tests. `alloc(n)` is all-or-nothing —
    a request either gets its blocks or None (the scheduler's admission
    gate turns None into queueing, never a crash).
    """

    def __init__(self, num_blocks: int) -> None:
        if num_blocks <= 1:
            raise ValueError(
                f"need at least 2 blocks (block {GARBAGE_BLOCK} is the "
                f"garbage block), got {num_blocks}"
            )
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(1, num_blocks))
        self._used: set = set()

    def alloc(self, n: int = 1) -> Optional[List[int]]:
        """n blocks, or None if fewer than n are free (all-or-nothing)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        blocks = self._free[:n]
        del self._free[:n]
        self._used.update(blocks)
        return blocks

    def free(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            if b not in self._used:
                raise ValueError(f"block {b} is not allocated")
            self._used.remove(b)
            self._free.append(b)

    @property
    def num_used(self) -> int:
        return len(self._used)

    @property
    def num_free(self) -> int:
        return len(self._free)


def make_paged_cache(model, num_blocks: int, block_size: int) -> Any:
    """Block-pool cache collection for `model` (decode mode).

    Mirrors the tree structure of `inference.make_cache` — same variable
    names per attention block, so `decode_apply` threads it unchanged —
    but every K/V leaf is `(num_blocks, block_size, h*hd)` instead of
    `(batch, max_len, h*hd)`. Scalar leaves (the flat layout's write
    cursors) stay for tree parity; the paged path never advances them.
    """
    if getattr(model, "kv_cache_dtype", None) == "int8":
        raise ValueError(
            "paged KV cache does not compose with kv_cache_dtype='int8' "
            "yet (the scales would need their own page pool)"
        )
    shapes = jax.eval_shape(lambda: make_cache(model, 1, block_size))
    return jax.tree.map(
        lambda a: jnp.zeros(a.shape, a.dtype) if a.ndim == 0
        else jnp.zeros((num_blocks,) + a.shape[1:], a.dtype),
        shapes,
    )


def scatter_prompt_blocks(pool: Any, scratch: Any, block_ids,
                          width: int, block_size: int) -> Any:
    """Scatter a batch-1 contiguous scratch cache into pool blocks.

    `scratch` holds a freshly prefilled prompt at positions `[0, width)`
    of a `(1, width, h*hd)` flat cache; `block_ids` is the
    `(ceil(width / block_size),)` int32 list of destination blocks (may
    be traced — admission happens inside jit). Chunk `i` of the scratch
    lands in pool block `block_ids[i]`; a trailing partial chunk writes
    only its real rows, so whatever the rest of that block held stays —
    and stays invisible, because attention is masked to the slot's own
    positions. Scalar leaves keep the POOL's value (no global clock).
    """
    n_chunks = -(-width // block_size)

    def per_leaf(p, s):
        if p.ndim == 0:
            return p
        for i in range(n_chunks):
            lo = i * block_size
            rows = min(block_size, width - lo)
            chunk = lax.dynamic_slice(
                s, (0, lo, 0), (1, rows, s.shape[2])
            ).astype(p.dtype)
            p = lax.dynamic_update_slice(p, chunk, (block_ids[i], 0, 0))
        return p

    return jax.tree.map(per_leaf, pool, scratch)
