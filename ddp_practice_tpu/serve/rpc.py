"""Transport seam for the cross-process serving fleet.

The router <-> replica boundary was DESIGNED in PRs 2/4 to be exactly
two operations — `Scheduler.submit(request)` going down and the
completions watermark coming back up (plus the health/evacuate edges
around them) — so making a replica a real OS process only requires a
wire under those two calls. This module is that wire: length-prefixed
JSON over a localhost TCP socket, stdlib only.

Framing: every message is a 4-byte big-endian length followed by that
many bytes of UTF-8 JSON. One request frame in, one response frame out,
strictly alternating per connection. JSON because every payload already
IS json-shaped (requests carry rid/prompt/deadline/priority/trace_id/
tenant, completions carry tokens/status/tenant/flight records — the
same dicts the telemetry stream writes), and because a human can
tcpdump it. The live ``trace`` op carries the sampling levers the same
way: ``sample`` (fleet head rate) and ``tenant_rates`` (per-tenant
overrides), applied by the worker without a restart.

Failure semantics (the part that matters for a chaos-tested fleet):

- every call has a TIMEOUT (socket-level). A worker that was SIGSTOPped
  mid-decode doesn't hang the router — the call raises `RpcTimeout`,
  the caller's heartbeat accounting decides whether that is a blip or a
  death (serve/supervisor.py feeds serve/health.py breakers).
- transport errors RETRY with the shared utils/backoff.py schedule —
  bounded attempts, deterministic jitter — reconnecting each time.
  Retrying is safe only because every operation is IDEMPOTENT at the
  worker: `submit` is deduplicated by rid, `poll` is a watermark read,
  `ping`/`shed`/`drain` are repeat-safe (serve/worker.py holds up that
  contract).
- an error REPLY (`{"ok": false, "error": ...}`) raises
  `RpcRemoteError` and is NOT retried: the frame made it, the handler
  rejected it — retrying would re-run a failing operation.

The server is deliberately small: an accept loop on a daemon thread,
one thread per connection, handlers dispatched from a dict. A handler
exception becomes an error reply, never a dead connection.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Callable, Dict, Optional

from ddp_practice_tpu.utils.backoff import backoff_delay

# one frame must hold a few thousand completions of a saturated poll;
# 64 MiB is ~3 orders of magnitude above that and still refuses a
# corrupt length prefix before it allocates the moon
MAX_FRAME_BYTES = 64 * 1024 * 1024
_LEN = struct.Struct(">I")


class RpcError(RuntimeError):
    """Transport-level failure: connect refused, peer closed, bad frame."""


class RpcTimeout(RpcError):
    """The per-call deadline expired (a stalled or SIGSTOPped peer)."""


class RpcRemoteError(RuntimeError):
    """The peer processed the frame and answered with an error —
    NOT a transport failure, never retried."""


# ----------------------------------------------------------------- framing
def send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode("utf-8")
    if len(data) > MAX_FRAME_BYTES:
        raise RpcError(f"frame too large: {len(data)} bytes")
    try:
        sock.sendall(_LEN.pack(len(data)) + data)
    except socket.timeout as e:
        raise RpcTimeout(f"send timed out: {e}") from e
    except OSError as e:
        raise RpcError(f"send failed: {e}") from e


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout as e:
            raise RpcTimeout(f"recv timed out: {e}") from e
        except OSError as e:
            raise RpcError(f"recv failed: {e}") from e
        if not chunk:
            raise RpcError("peer closed the connection mid-frame"
                           if buf else "peer closed the connection")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket) -> dict:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME_BYTES:
        raise RpcError(f"frame length {n} exceeds {MAX_FRAME_BYTES}")
    try:
        return json.loads(_recv_exact(sock, n).decode("utf-8"))
    except ValueError as e:
        raise RpcError(f"bad frame payload: {e}") from e


# ------------------------------------------------------------------ client
class RpcClient:
    """One persistent connection to a worker, with per-call timeouts and
    bounded reconnect-retries on transport failure.

    NOT thread-safe by design — the router's tick loop is the single
    caller (`call` holds a lock anyway as a belt, so a stray second
    thread serializes instead of interleaving frames).
    """

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 5.0,
                 retries: int = 2,
                 retry_base_s: float = 0.02,
                 retry_max_s: float = 0.5,
                 seed: int = 0,
                 sleep: Callable[[float], None] = None) -> None:
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.retries = retries
        self.retry_base_s = retry_base_s
        self.retry_max_s = retry_max_s
        self.seed = seed
        self._sleep = sleep if sleep is not None else time.sleep
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s
            )
        except OSError as e:
            raise RpcError(f"connect to {self.host}:{self.port} "
                           f"failed: {e}") from e
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def call(self, op: str, *, timeout_s: Optional[float] = None,
             retries: Optional[int] = None, **fields) -> dict:
        """One request/response round trip. Raises RpcTimeout /
        RpcError after the retry budget, RpcRemoteError immediately on
        an error reply. `timeout_s`/`retries` override the client
        defaults per call (a heartbeat wants to fail FAST and let the
        caller's staleness accounting judge; a submit can afford the
        full budget)."""
        req = {"op": op, **fields}
        deadline = timeout_s if timeout_s is not None else self.timeout_s
        budget = retries if retries is not None else self.retries
        last: Optional[Exception] = None
        with self._lock:
            for attempt in range(budget + 1):
                if attempt:
                    self._sleep(backoff_delay(
                        attempt - 1, base_s=self.retry_base_s,
                        max_s=self.retry_max_s, seed=self.seed,
                    ))
                try:
                    if self._sock is None:
                        self._sock = self._connect()
                    self._sock.settimeout(deadline)
                    send_frame(self._sock, req)
                    reply = recv_frame(self._sock)
                except RpcError as e:
                    # transport failure: the connection state is
                    # unknowable (a frame may be half-written) — drop
                    # it and reconnect on the next attempt. Safe
                    # because worker ops are idempotent (module doc).
                    self._drop()
                    last = e
                    continue
                if not reply.get("ok", False):
                    raise RpcRemoteError(
                        f"{op}: {reply.get('error', 'unknown error')}"
                    )
                return reply
        raise last  # type: ignore[misc]

    def cast(self, op: str, *, timeout_s: Optional[float] = None,
             **fields) -> None:
        """One-way send: ship the frame, read NO reply. The frame is
        flagged `oneway` so the server skips its response (see
        _serve_conn) — an unread reply left in the socket would desync
        the next call() on this connection. Delivery is NOT confirmed:
        callers must be idempotent and reconcile (the fleet submit path
        confirms by rid on the next poll and resubmits what never
        landed). One reconnect attempt on transport failure, then the
        error propagates — the caller's breaker accounting judges."""
        req = {"op": op, "oneway": True, **fields}
        deadline = timeout_s if timeout_s is not None else self.timeout_s
        last: Optional[Exception] = None
        with self._lock:
            for _ in range(2):
                try:
                    if self._sock is None:
                        self._sock = self._connect()
                    self._sock.settimeout(deadline)
                    send_frame(self._sock, req)
                    return
                except RpcError as e:
                    self._drop()
                    last = e
        raise last  # type: ignore[misc]

    def close(self) -> None:
        with self._lock:
            self._drop()

    def __enter__(self) -> "RpcClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ------------------------------------------------------- client-side stream
class FrameStream:
    """Buffered NON-BLOCKING frame reader over a connected socket — the
    client side of a push subscription (serve/worker.py `subscribe`).
    `drain()` returns every complete frame currently available without
    ever waiting: the router calls it once per tick, so steady-state
    completion delivery costs no round trips at all (the poll op stays
    as the reconciliation/recovery path)."""

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._sock.setblocking(False)
        self._buf = bytearray()
        self._closed = False

    def fileno(self) -> int:
        """The underlying fd — a select()-driven caller sleeps on this
        and wakes exactly when the server pushes (no polling, no
        sleep-quantized consumption lag)."""
        return self._sock.fileno()

    def drain(self) -> list:
        while not self._closed:
            try:
                chunk = self._sock.recv(65536)
            except (BlockingIOError, InterruptedError):
                break
            except OSError as e:
                raise RpcError(f"stream recv failed: {e}") from e
            if not chunk:
                # peer closed (e.g. the worker died) — but the kernel
                # buffer may still hold frames pushed BEFORE the death:
                # parse and return them first, raise on the NEXT drain.
                # A SIGKILLed worker's final pub frame carries the
                # freshest salvage point + chunk slice; discarding it
                # here would widen every failover's resume gap.
                self._closed = True
                break
            self._buf.extend(chunk)
        frames = []
        while len(self._buf) >= _LEN.size:
            (n,) = _LEN.unpack(bytes(self._buf[:_LEN.size]))
            if n > MAX_FRAME_BYTES:
                raise RpcError(f"stream frame length {n} exceeds cap")
            if len(self._buf) < _LEN.size + n:
                break
            try:
                frames.append(json.loads(
                    bytes(self._buf[_LEN.size:_LEN.size + n])
                ))
            except ValueError as e:
                raise RpcError(f"bad stream frame: {e}") from e
            del self._buf[:_LEN.size + n]
        if self._closed and not frames:
            raise RpcError("stream peer closed")
        return frames

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def open_stream(host: str, port: int, op: str = "subscribe",
                timeout_s: float = 5.0, **fields) -> FrameStream:
    """Connect, send one `op` frame, await the ok reply, then hand the
    socket over as a FrameStream the SERVER pushes to from now on."""
    try:
        sock = socket.create_connection((host, port), timeout=timeout_s)
    except OSError as e:
        raise RpcError(f"stream connect failed: {e}") from e
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(timeout_s)
    send_frame(sock, {"op": op, **fields})
    reply = recv_frame(sock)
    if not reply.get("ok", False):
        sock.close()
        raise RpcRemoteError(
            f"{op}: {reply.get('error', 'unknown error')}"
        )
    return FrameStream(sock)


# ------------------------------------------------------------------ server
class RpcServer:
    """Threaded frame server: `handlers[op](request_dict) -> dict`.

    A handler's return dict is sent as `{"ok": true, **result}`; a
    handler exception becomes `{"ok": false, "error": ...}` on the same
    connection (the caller sees RpcRemoteError, the connection lives).
    `port=0` binds an ephemeral port (read `.port`). Handlers run on
    the connection's thread — the worker serializes state mutation with
    its own lock (serve/worker.py), not here.

    PUSH MODE: a handler may return ``{"_stream_queue": q, ...}`` — the
    ok reply (without that key) is sent, then the connection's thread
    stops reading requests and instead DRAINS `q` (a queue.Queue),
    sending each item as a frame until the queue yields a ``None``
    sentinel, the peer goes away, or the server closes. The producer
    (serve/worker.py `_publish`) never touches the socket — one thread
    owns it for life, so pushes cannot interleave with replies.

    Pushed frames are kind-tagged dicts; the worker currently emits
    ``pub`` (completions watermark + per-burst TokenChunk slice with
    its own ``chunks_watermark`` + inflight salvage + stats — chunks
    ride IN the pub frame, not a separate kind, so a dropped frame
    loses the chunk slice and the salvage point together and the
    client's resume cursor can never outrun delivery), ``hb`` (idle
    heartbeat), and ``trace`` (batched span records for the fleet
    TraceCollector, seq-numbered with a cumulative drop count).
    The transport is deliberately agnostic: new kinds ride for free,
    and unknown kinds are skipped by consumers.
    """

    def __init__(self, handlers: Dict[str, Callable[[dict], dict]], *,
                 host: str = "127.0.0.1", port: int = 0,
                 start: bool = True) -> None:
        self.handlers = handlers
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
        )
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.host = host
        self.port = self._listener.getsockname()[1]
        self._stop = threading.Event()
        self._threads: list = []
        self._conns: set = set()       # live sockets, closed on close()
        self._conn_lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def start(self) -> None:
        if self._accept_thread is not None:
            return
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rpc-accept", daemon=True
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        try:
            self._listener.settimeout(0.2)
        except OSError:
            return  # close() won the race before the first accept
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed under us (close())
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_lock:
                self._conns.add(conn)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="rpc-conn", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                while not self._stop.is_set():
                    try:
                        req = recv_frame(conn)
                    except RpcError:
                        return  # peer went away (or garbage): done
                    op = req.get("op")
                    handler = self.handlers.get(op)
                    stream_q = stream_closed = None
                    try:
                        if handler is None:
                            raise KeyError(f"unknown op {op!r}")
                        reply = {"ok": True, **(handler(req) or {})}
                        stream_q = reply.pop("_stream_queue", None)
                        stream_closed = reply.pop("_stream_closed", None)
                    except BaseException as e:  # a handler bug must
                        reply = {"ok": False,   # answer, not kill the
                                 "error":       # connection
                                 f"{type(e).__name__}: {e}"}
                    if req.get("oneway"):
                        # fire-and-forget frame (RpcClient.cast): the
                        # client reads no reply, so sending one — even
                        # an error — would be read as the NEXT call's
                        # response and desync the connection. Errors
                        # surface through the caller's reconcile path.
                        continue
                    try:
                        send_frame(conn, reply)
                    except RpcError:
                        return
                    if stream_q is not None:
                        try:
                            self._push_loop(conn, stream_q)
                        finally:
                            # tell the producer its subscriber is gone
                            # (a reconnect-happy client must not leak
                            # one dead queue per drop)
                            if stream_closed is not None:
                                try:
                                    stream_closed()
                                except Exception:
                                    pass
                        return
        finally:
            with self._conn_lock:
                self._conns.discard(conn)

    def _push_loop(self, conn: socket.socket, q) -> None:
        """Own the connection as a push stream: send queue items as
        frames until a None sentinel, peer loss, or server stop."""
        import queue as _queue

        # a subscriber that stops reading must not wedge this thread:
        # a timed-out send drops the stream (the client's poll path is
        # the recovery)
        conn.settimeout(1.0)
        while not self._stop.is_set():
            try:
                item = q.get(timeout=0.25)
            except _queue.Empty:
                continue
            if item is None:
                return
            try:
                send_frame(conn, item)
            except RpcError:
                return

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        # close live connections so their threads' blocking recv wakes
        # up NOW — a closed server must stop answering, not keep serving
        # stale handlers through established sockets
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None
        for t in self._threads:
            t.join(timeout=0.5)
        self._threads.clear()

    def __enter__(self) -> "RpcServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
