"""Draft-free speculative decoding: prompt-lookup draft proposals.

Speculative decoding (Leviathan et al., 2023) splits a decode step in
two: a cheap DRAFTER proposes the next k tokens, the real model then
scores the whole drafted window in ONE forward pass and accepts the
longest prefix it agrees with. Under greedy decoding the acceptance
rule is exact string matching against the model's own argmaxes, so the
emitted stream is bit-identical to plain decode — speculation is purely
a latency lever, never a quality knob.

This module is the DRAFT half. It is draft-free in the model sense:
no second network, no extra device state. The `PromptLookupDraft`
drafter (the vLLM "prompt lookup" / n-gram idea) matches the trailing
n-gram of a request's context (prompt + generated tokens, both
host-known) against earlier occurrences in that same context and
proposes the tokens that followed the most recent earlier occurrence.
Summarization, code editing, chat-with-quotes and the shared-prefix
traffic the PR-6 radix cache targets all repeat long spans of their
own prompt, which is exactly when this trivial drafter hits.

The VERIFY half lives in serve/engine.py (`PagedEngine.step_verify`):
a jitted k-token paged-prefill forward over the drafted window plus
exact greedy acceptance and a block-aware `kv_lengths` rollback of the
rejected tail. The two halves meet at the `DraftSource` interface so a
real small-model drafter can slot in later without touching the engine
(ROADMAP item 2's remaining half).

Everything here is host-pure (lists and dicts, no jax) — drafting must
cost microseconds, not a dispatch. Proposals are best-effort hints: a
wrong draft costs only wasted verify FLOPs, never a wrong token.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple


class DraftSource:
    """What the engine needs from a drafter: per-slot context tracking
    plus a `propose` that returns up to k candidate next tokens.

    Lifecycle (driven by PagedEngine): `begin(slot, prompt)` at
    admission (readmission after preemption passes prompt + salvaged
    tokens — the drafter never needs to survive a preempt), `extend`
    with every emitted token run, `end(slot)` at release/preempt.
    Slots are dense small ints, reused after release.
    """

    def begin(self, slot: int, context: Sequence[int]) -> None:
        raise NotImplementedError

    def extend(self, slot: int, tokens: Sequence[int]) -> None:
        raise NotImplementedError

    def propose(self, slot: int, k: int) -> List[int]:
        """Up to k draft tokens for the slot's NEXT positions ([] = no
        proposal this step; the engine then falls back to plain
        decode for the slot — one real token, zero waste)."""
        raise NotImplementedError

    def end(self, slot: int) -> None:
        raise NotImplementedError

    def snapshot(self, slot: int) -> List[int]:
        """The slot's tracked context, for seeding a fork sibling's
        drafter state (`PagedEngine.fork`). Drafters that keep no
        replayable context may return [] — proposals are hints, so a
        cold-started sibling costs acceptance, never correctness."""
        return []


class PromptLookupDraft(DraftSource):
    """N-gram prompt-lookup drafter with an incremental index.

    Per slot it keeps the full context (prompt + generated) and, for
    each n in [ngram_min, ngram_max], a dict mapping every n-gram seen
    so far to the position RIGHT AFTER its most recent occurrence
    (insertion order means later occurrences overwrite earlier ones —
    recency wins, matching the intuition that the latest use of a
    phrase predicts its next continuation best). `propose` looks up the
    context's trailing n-gram, longest n first, and returns the tokens
    that followed the match — then CHAINS: the draft's own tail becomes
    the next lookup gram, so a match near the context's end (where the
    raw continuation would truncate after a token or two) keeps
    extending through the repetition until k tokens are drafted or no
    gram matches. On self-repeating text — quoted spans, cycles, the
    lookup drafter's whole hunting ground — chaining is the difference
    between 2-token and full-k drafts, and verify amortizes its fixed
    two-apply dispatch over k+1 tokens instead of 3.

    The index grows by one dict entry per (token, n) — `extend` is
    O(len(tokens) * n_sizes), `propose` is O(k * n_sizes) — so drafting
    stays far below dispatch cost however long contexts get. A gram
    ending at the context's last token is NOT yet indexed (its
    continuation hasn't happened), which is what keeps `propose` from
    matching the trailing gram against itself.
    """

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1) -> None:
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError(
                f"need 1 <= ngram_min <= ngram_max, got "
                f"[{ngram_min}, {ngram_max}]"
            )
        self.ngram_max = ngram_max
        self.ngram_min = ngram_min
        self._ctx: Dict[int, List[int]] = {}
        # slot -> {n -> {gram tuple -> continuation position}}
        self._index: Dict[int, Dict[int, Dict[Tuple[int, ...], int]]] = {}

    # ------------------------------------------------------------ lifecycle
    def begin(self, slot: int, context: Sequence[int]) -> None:
        self._ctx[slot] = []
        self._index[slot] = {
            n: {} for n in range(self.ngram_min, self.ngram_max + 1)
        }
        self.extend(slot, context)

    def extend(self, slot: int, tokens: Sequence[int]) -> None:
        ctx = self._ctx[slot]
        index = self._index[slot]
        for tok in tokens:
            i = len(ctx)  # the new token's position
            # the arrival of token i completes the continuation of
            # every gram ENDING at i-1: register gram -> i
            for n, grams in index.items():
                if i >= n:
                    grams[tuple(ctx[i - n:i])] = i
            ctx.append(int(tok))

    def end(self, slot: int) -> None:
        self._ctx.pop(slot, None)
        self._index.pop(slot, None)

    # -------------------------------------------------------------- drafting
    def propose(self, slot: int, k: int) -> List[int]:
        ctx = self._ctx.get(slot)
        if ctx is None or k <= 0:
            return []
        index = self._index[slot]
        draft: List[int] = []
        while len(draft) < k:
            # the lookup tail spans ctx + draft-so-far; only its last
            # ngram_max tokens can matter, so no full-context copies
            tail = (ctx[max(0, len(ctx) - self.ngram_max):] + draft)[
                -self.ngram_max:]
            total = len(ctx) + len(draft)
            nxt: List[int] = []
            for n in range(self.ngram_max, self.ngram_min - 1, -1):
                if total < n:
                    continue
                pos = index[n].get(tuple(tail[-n:]))
                if pos is None:
                    continue
                # pos <= len(ctx) - 1 always (a gram ending at the last
                # token has no continuation yet), so this is non-empty
                nxt = ctx[pos:pos + (k - len(draft))]
                break
            if not nxt:
                break
            draft.extend(nxt)
        return draft

    # ------------------------------------------------------------- observers
    def snapshot(self, slot: int) -> List[int]:
        return list(self._ctx.get(slot, []))

    def context_len(self, slot: int) -> int:
        """Tracked context length (tests; -1 for an unknown slot)."""
        ctx = self._ctx.get(slot)
        return -1 if ctx is None else len(ctx)
