"""Server-Sent Events codec for the serving front door.

One StreamEvent (serve/router.py) maps to one SSE frame, losslessly::

    id: <seq>
    event: <tokens|resumed|end>
    data: {"start": 0, "tokens": [5, 9], ...}
    <blank line>

The mapping is deliberately 1:1 with the in-process exactly-once
contract: ``id`` IS the stream's contiguous ``seq`` (so a wire capture
can be audited by the same rules tools/check_stream.py applies to the
router's telemetry JSONL — contiguous ids from 0, exactly one terminal
frame), ``event`` IS the typed kind, and ``data`` carries the rest of
the StreamEvent as JSON. Nothing is added on the wire that the
in-process consumer would not see, and nothing is dropped — a consumer
reading frames learns exactly what `TokenStream.events` records.

Both halves live here: `encode_event` (server -> wire) and `SSEParser`
(wire -> events, incremental, boundary-safe), so the bench's client
and the server share one codec and a framing bug cannot hide between
two implementations. Pure stdlib, no I/O — the front door owns sockets.
"""

from __future__ import annotations

import json
from typing import List, Optional

# the event kinds the wire may carry — the StreamEvent kinds plus
# "error", the front door's pre-stream failure frame (a request that
# never reached the router still ends with a typed terminal, never a
# dropped connection)
KINDS = ("tokens", "resumed", "end", "error")


def encode_event(kind: str, seq: int, data: dict) -> bytes:
    """One SSE frame. `data` must be JSON-serializable; newlines inside
    the payload are impossible by construction (json.dumps never emits
    raw newlines), so the single `data:` line framing is safe."""
    payload = json.dumps(data, separators=(",", ":"), sort_keys=True)
    return (f"id: {seq}\nevent: {kind}\ndata: {payload}\n\n").encode()


def encode_stream_event(ev) -> bytes:
    """A router StreamEvent onto the wire, field-for-field."""
    data = {"start": ev.start, "tokens": list(ev.tokens)}
    if ev.status is not None:
        data["status"] = ev.status
    if ev.attrs:
        data["attrs"] = ev.attrs
    if ev.trace_id is not None:
        data["trace_id"] = ev.trace_id
    return encode_event(ev.kind, ev.seq, data)


class SSEParser:
    """Incremental SSE decoder: feed raw bytes (any chunking — a frame
    may arrive split across TCP segments, or many per segment), collect
    complete events. Tolerates \\r\\n and \\n line endings; unknown
    field names are ignored per the SSE spec."""

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, data: bytes) -> List[dict]:
        """Returns the events completed by this chunk, in order. Each
        is ``{"id": int|None, "event": str, "data": dict|str}`` —
        `data` is parsed JSON when it parses, the raw string otherwise
        (the audit distinguishes malformed payloads from absent ones)."""
        self._buf += data
        out: List[dict] = []
        while True:
            # a frame ends at the first blank line (either ending)
            cut, sep = self._find_frame_end()
            if cut < 0:
                return out
            frame, self._buf = self._buf[:cut], self._buf[cut + sep:]
            ev = self._parse_frame(frame)
            if ev is not None:
                out.append(ev)

    def _find_frame_end(self):
        a = self._buf.find(b"\n\n")
        b = self._buf.find(b"\r\n\r\n")
        if a < 0 and b < 0:
            return -1, 0
        if b < 0 or (0 <= a < b):
            return a, 2
        return b, 4

    @staticmethod
    def _parse_frame(frame: bytes) -> Optional[dict]:
        ev_id: Optional[int] = None
        kind = "message"          # the SSE default event name
        data_lines: List[str] = []
        for raw in frame.decode("utf-8", "replace").splitlines():
            if not raw or raw.startswith(":"):
                continue          # comment / keep-alive line
            name, _, value = raw.partition(":")
            value = value[1:] if value.startswith(" ") else value
            if name == "id":
                try:
                    ev_id = int(value)
                except ValueError:
                    ev_id = None
            elif name == "event":
                kind = value
            elif name == "data":
                data_lines.append(value)
        if not data_lines and ev_id is None and kind == "message":
            return None           # pure comment frame
        text = "\n".join(data_lines)
        try:
            data = json.loads(text) if text else {}
        except ValueError:
            data = text
        return {"id": ev_id, "event": kind, "data": data}
