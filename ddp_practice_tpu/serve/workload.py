"""Deterministic multi-tenant workload plans for the QoS lab.

The single-tenant trace builders in serve/bench.py (Poisson arrivals,
uniform lengths) can show throughput but cannot show FAIRNESS: every
interesting QoS failure needs at least two tenants with different
shapes — a hostile tenant flooding at several times its share while a
compliant tenant trickles, bursts landing on a diurnal trough, long
heavy-tailed prompts starving short interactive ones. This module makes
that mix a first-class, REPLAYABLE input, the same way serve/faults.py
made failures one: a WorkloadPlan is a list of TenantSpecs serialized
as JSON, and ``build(vocab=..., seed=...)`` expands it into the same
arrival-sorted trace-dict list the bench harness already replays —
identical every time for a given (plan, vocab, seed), so the fair and
FIFO arms of a bench see byte-identical offered load.

Per-tenant knobs (each one a real traffic shape):

- ``arrivals`` — "poisson" (memoryless baseline), "bursty" (rate jumps
  ``burst_mult``x inside periodic windows: retry storms, cron fanout),
  or "diurnal" (sinusoidal rate: the day/night cycle compressed to
  ``diurnal_period_s``). Non-homogeneous processes are sampled by
  Lewis thinning against the peak rate, so the draw count — and hence
  determinism — does not depend on where the bursts land.
- heavy-tailed lengths — prompt and output budgets are lognormal
  (``*_mean``/``*_sigma``) capped at ``*_cap``: most requests short, a
  tail of monsters, which is what real prompt-length histograms look
  like and what uniform ranges hide.
- ``sessions``/``turns_per_session`` — multi-turn chat: each session's
  turn N re-feeds the whole conversation so far (prefix + every prior
  tail) plus a fresh tail, which is exactly the traffic the radix
  prefix cache (serve/kv_pages.py) exists for. Turns of one session
  arrive in order; sessions interleave.
- ``hostile`` — marks the tenant whose traffic is the attack in an
  isolation experiment. The flag changes NOTHING about generation
  (hostility is just a rate several times the fair share — set
  ``rate_rps`` accordingly); it tells consumers (the qos bench arm,
  tools/check_qos.py) which tenant's SLO alert SHOULD trip and whose
  must not.

Trace rows carry ``tenant`` and ``priority``, which Request already
threads through every seam (admission -> scheduler -> SLO attribution),
so a plan drives the whole QoS plane with no new plumbing.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import List, Optional, Sequence

import numpy as np

_ARRIVALS = ("poisson", "bursty", "diurnal")


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's traffic shape. Defaults are a small, polite,
    single-turn Poisson tenant; every field is a JSON key."""

    name: str
    rate_rps: float = 1.0
    arrivals: str = "poisson"
    burst_every_s: float = 10.0   # bursty: window period
    burst_len_s: float = 1.0      # bursty: window length
    burst_mult: float = 8.0       # bursty: in-window rate multiplier
    diurnal_period_s: float = 60.0  # diurnal: sinusoid period
    diurnal_depth: float = 0.8      # diurnal: amplitude in [0, 1)
    prompt_len_mean: float = 12.0   # lognormal median, tokens
    prompt_len_sigma: float = 0.6
    prompt_len_cap: int = 96
    max_new_mean: float = 12.0
    max_new_sigma: float = 0.5
    max_new_cap: int = 48
    sessions: int = 0             # >0: multi-turn mode, this many chats
    turns_per_session: int = 1
    session_prefix_len: int = 24  # shared system-prompt length per chat
    priority: int = 0
    hostile: bool = False

    def __post_init__(self):
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        if self.rate_rps <= 0:
            raise ValueError(f"{self.name}: rate_rps must be > 0")
        if self.arrivals not in _ARRIVALS:
            raise ValueError(f"{self.name}: arrivals {self.arrivals!r}; "
                             f"one of {_ARRIVALS}")
        if self.arrivals == "bursty" and (
                self.burst_every_s <= 0 or self.burst_len_s <= 0
                or self.burst_len_s > self.burst_every_s
                or self.burst_mult < 1.0):
            raise ValueError(f"{self.name}: bursty needs 0 < burst_len_s"
                             " <= burst_every_s and burst_mult >= 1")
        if self.arrivals == "diurnal" and not (
                0.0 <= self.diurnal_depth < 1.0
                and self.diurnal_period_s > 0):
            raise ValueError(f"{self.name}: diurnal needs depth in "
                             "[0, 1) and period > 0")
        for fld in ("prompt_len_mean", "prompt_len_sigma",
                    "max_new_mean", "max_new_sigma"):
            if getattr(self, fld) < 0:
                raise ValueError(f"{self.name}: {fld} must be >= 0")
        if self.prompt_len_cap < 1 or self.max_new_cap < 1:
            raise ValueError(f"{self.name}: length caps must be >= 1")
        if self.sessions < 0 or self.turns_per_session < 1:
            raise ValueError(f"{self.name}: sessions >= 0, "
                             "turns_per_session >= 1")
        if self.sessions > 0 and self.session_prefix_len < 1:
            raise ValueError(f"{self.name}: session_prefix_len >= 1")

    # ------------------------------------------------------------ rates
    def peak_rate(self) -> float:
        if self.arrivals == "bursty":
            return self.rate_rps * self.burst_mult
        if self.arrivals == "diurnal":
            return self.rate_rps * (1.0 + self.diurnal_depth)
        return self.rate_rps

    def rate_at(self, t: float) -> float:
        """Instantaneous rate at clock second `t` (thinning target)."""
        if self.arrivals == "bursty":
            in_burst = (t % self.burst_every_s) < self.burst_len_s
            return self.rate_rps * (self.burst_mult if in_burst else 1.0)
        if self.arrivals == "diurnal":
            phase = 2.0 * math.pi * t / self.diurnal_period_s
            return self.rate_rps * (1.0 + self.diurnal_depth
                                    * math.sin(phase))
        return self.rate_rps


class WorkloadPlan:
    """An ordered, serializable set of TenantSpecs plus a duration."""

    def __init__(self, tenants: Sequence[TenantSpec],
                 duration_s: float = 10.0) -> None:
        if not tenants:
            raise ValueError("a workload plan needs at least one tenant")
        names = [t.name for t in tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names: {names}")
        if duration_s <= 0:
            raise ValueError("duration_s must be > 0")
        self.tenants: List[TenantSpec] = list(tenants)
        self.duration_s = float(duration_s)

    # --------------------------------------------------------------- json
    @classmethod
    def from_json(cls, src: str) -> "WorkloadPlan":
        """Parse a plan from a JSON string or a path to a JSON file.

        Schema: {"duration_s": ..., "tenants": [{"name": ..., ...}]} —
        or a bare list of tenant objects (default duration).
        """
        text = src
        if not src.lstrip().startswith(("{", "[")):
            # same rule as serve/faults.py FaultPlan: a mistyped path
            # must fail as a missing file, not a JSON decode error
            if not os.path.exists(src):
                raise FileNotFoundError(
                    f"workload plan {src!r}: not inline JSON and "
                    "no such file")
            with open(src) as f:
                text = f.read()
        data = json.loads(text)
        if isinstance(data, list):
            return cls([TenantSpec(**item) for item in data])
        return cls(
            [TenantSpec(**item) for item in data.get("tenants", [])],
            duration_s=data.get("duration_s", 10.0),
        )

    def to_json(self) -> str:
        return json.dumps({
            "duration_s": self.duration_s,
            "tenants": [dataclasses.asdict(t) for t in self.tenants],
        })

    def hostile_tenants(self) -> List[str]:
        return [t.name for t in self.tenants if t.hostile]

    # -------------------------------------------------------------- build
    def build(self, *, vocab: int, seed: int = 0) -> list:
        """Expand the plan into an arrival-sorted bench trace.

        Each row: {rid, arrival, prompt, max_new_tokens, tenant,
        priority}. rids are assigned AFTER the cross-tenant sort, so
        rid order == arrival order (what replay harnesses assume).
        Each tenant draws from its own child generator (spawned off the
        plan seed by tenant INDEX), so adding a tenant to the end of a
        plan never perturbs the traffic of the ones before it.
        """
        if vocab < 2:
            raise ValueError("vocab must be >= 2")
        rows: list = []
        root = np.random.SeedSequence(seed)
        children = root.spawn(len(self.tenants))
        for spec, child in zip(self.tenants, children):
            rng = np.random.default_rng(child)
            arrivals = _thinned_arrivals(spec, self.duration_s, rng)
            rows.extend(_tenant_rows(spec, arrivals, vocab, rng))
        rows.sort(key=lambda r: (r["arrival"], r["tenant"]))
        for i, row in enumerate(rows):
            row["rid"] = i
        return rows


def _thinned_arrivals(spec: TenantSpec, duration_s: float,
                      rng) -> List[float]:
    """Lewis thinning: draw a homogeneous Poisson stream at the PEAK
    rate, keep each point with probability rate(t)/peak. The candidate
    draw count is independent of the rate shape, which keeps the
    stream deterministic under spec edits that only move bursts."""
    peak = spec.peak_rate()
    out: List[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / peak))
        if t >= duration_s:
            return out
        if float(rng.random()) * peak <= spec.rate_at(t):
            out.append(t)


def _lognormal_len(rng, mean: float, sigma: float, cap: int) -> int:
    """Heavy-tailed length: lognormal with median `mean`, clamped to
    [1, cap]. sigma 0 degenerates to the constant `mean`."""
    draw = mean * float(np.exp(rng.normal(0.0, sigma))) if sigma > 0 \
        else mean
    return max(1, min(cap, int(round(draw))))


def _tenant_rows(spec: TenantSpec, arrivals: List[float], vocab: int,
                 rng) -> list:
    rows = []
    if spec.sessions > 0:
        # multi-turn: each arrival is the next turn of a round-robin
        # session; a turn's prompt is the WHOLE conversation so far
        # (prefix + all prior tails) plus its fresh tail — the re-fed
        # history is what exercises the prefix cache
        prefixes = [
            rng.integers(0, vocab, spec.session_prefix_len).tolist()
            for _ in range(spec.sessions)
        ]
        history = [list(p) for p in prefixes]
        turns = [0] * spec.sessions
        for k, at in enumerate(arrivals):
            s = k % spec.sessions
            if turns[s] >= spec.turns_per_session:
                history[s] = list(prefixes[s])  # chat over: new one
                turns[s] = 0
            tail = rng.integers(0, vocab, _lognormal_len(
                rng, spec.prompt_len_mean, spec.prompt_len_sigma,
                spec.prompt_len_cap)).tolist()
            prompt = history[s] + tail
            history[s] = prompt
            turns[s] += 1
            rows.append(_row(spec, at, prompt, rng))
    else:
        for at in arrivals:
            prompt = rng.integers(0, vocab, _lognormal_len(
                rng, spec.prompt_len_mean, spec.prompt_len_sigma,
                spec.prompt_len_cap)).tolist()
            rows.append(_row(spec, at, prompt, rng))
    return rows


def _row(spec: TenantSpec, at: float, prompt: list, rng) -> dict:
    return {
        "rid": -1,  # assigned after the cross-tenant sort
        "arrival": float(at),
        "prompt": prompt,
        "max_new_tokens": _lognormal_len(
            rng, spec.max_new_mean, spec.max_new_sigma,
            spec.max_new_cap),
        "tenant": spec.name,
        "priority": spec.priority,
    }
