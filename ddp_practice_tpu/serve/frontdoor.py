"""The serving front door: HTTP/SSE over `Router.stream`.

Everything below PR 15 terminates at a Python API — `Router.submit`
plus a `TokenStream` readable only in-process. This module is the wire
surface the ROADMAP's "heavy traffic" story needs: a hand-rolled
asyncio HTTP/1.1 server speaking

    POST /v1/generate        -> 200 text/event-stream (chunked)
    GET  /healthz            -> 200 application/json

where the SSE frames ARE the router's typed StreamEvents (serve/sse.py
— ``tokens`` / ``resumed`` / ``end``, contiguous ids, exactly one
terminal), so the exactly-once contract the in-process consumer gets
is the contract the socket consumer gets, auditable by the same tool
(tools/check_stream.py --sse).

Architecture — one pump, many readers:

- `RouterDriver` owns the router on a dedicated thread: it holds THE
  lock, calls `router.step()` whenever work is pending, and fans each
  stream's new events out to per-connection subscribers. The router
  and everything under it (scheduler, engine, jax dispatch) stay
  single-threaded — exactly the discipline the rest of the repo
  assumes — and the asyncio side never touches router state directly.
- Each connection gets a bounded event buffer. The asyncio writer
  applies real TCP backpressure (`await drain()`); when a consumer is
  slower than its stream for long enough to fill the buffer, the
  subscription is SHED: buffered frames are dropped, the wire gets one
  synthetic terminal (``end`` / status ``slow_consumer``), and the
  request itself keeps running to completion on the engine. A slow
  reader therefore never pins KV blocks or stalls the decode loop —
  the engine never waits on any consumer, and the bound caps what a
  dead-slow socket can hold in router-side memory.
- Intake order at the door: parse -> auth (401) -> validation (400) ->
  drain check (503) -> per-tenant admission (429, serve/admission.py)
  -> `router.submit`. Anything past submit is SSE: even a brown-out
  shed at the router door rides out as a 200 stream whose only frame
  is the typed ``end`` — never silence, never a dropped connection.
- Graceful drain mirrors the worker's SIGTERM path (serve/worker.py):
  `begin_drain()` flips new generates to 503 while in-flight streams
  finish; `install_sigterm()` hangs that on the signal, and `drain()`
  blocks until the floor is clear (bounded by a timeout).

The client half (`sse_request`) lives here too — a blocking
socket-level SSE consumer the bench and tests use, sharing the codec
with the server so the wire format is pinned by construction on both
ends.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import select
import signal
import socket
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ddp_practice_tpu.serve.admission import AdmissionController
from ddp_practice_tpu.serve.scheduler import Request
from ddp_practice_tpu.serve.sse import SSEParser, encode_event
from ddp_practice_tpu.utils.trace import ROUTER_PID


@dataclasses.dataclass(frozen=True)
class FrontdoorConfig:
    host: str = "127.0.0.1"
    port: int = 0                      # 0 = ephemeral (the test default)
    # request validation bounds (400 past either)
    max_prompt_len: int = 4096
    max_new_tokens: int = 1024
    max_body_bytes: int = 1 << 20
    # static bearer-token auth when set (the `auth` hook overrides)
    auth_token: Optional[str] = None
    # consumer backpressure: per-connection buffered SSE events before
    # the stream is shed with a synthetic ``end``/``slow_consumer``.
    # The event bound only bites while the writer is parked on TCP
    # backpressure, so the two buffer knobs below size how much a slow
    # reader can absorb before `drain()` blocks: the asyncio transport
    # high-watermark and the kernel send buffer (SO_SNDBUF, inherited
    # by accepted connections; None = platform default). Tests shrink
    # all three to provoke the shed path deterministically.
    max_buffered_events: int = 256
    write_buffer_bytes: int = 65536
    sndbuf: Optional[int] = None
    # driver pacing while the fleet is idle (busy loops never sleep)
    idle_sleep_s: float = 0.002
    header_timeout_s: float = 10.0


class _Subscriber:
    """One connection's slice of a TokenStream: the driver appends
    events under its lock; the asyncio handler drains under the same
    lock and blocks on the socket in between. `shed` is one-way."""

    __slots__ = ("rid", "tenant", "events", "limit", "shed", "loop",
                 "wake", "cursor")

    def __init__(self, rid: int, tenant: Optional[str], limit: int,
                 loop, wake) -> None:
        self.rid = rid
        self.tenant = tenant
        self.events: deque = deque()
        self.limit = limit
        self.shed = False
        self.loop = loop
        self.wake = wake          # asyncio.Event, set via the loop
        self.cursor = 0           # TokenStream.events consumed so far


class RouterDriver:
    """The router's single-threaded pump with a fan-out seam.

    All router access — submits from connection handlers, the step
    loop, event fan-out, reaping — happens under `self.lock`, so the
    stack below keeps its single-threaded invariants while any number
    of asyncio connections read their own buffers."""

    def __init__(self, router, *, idle_sleep_s: float = 0.002,
                 max_buffered_events: int = 256) -> None:
        self.router = router
        self.lock = threading.RLock()
        self.idle_sleep_s = idle_sleep_s
        self.max_buffered_events = max_buffered_events
        self._subs: Dict[int, _Subscriber] = {}
        self._owned: set = set()   # rids this driver submitted
        # rids must never repeat for the router's lifetime (duplicate
        # detection keys on them) — continue past anything already
        # tracked so a driver can share a router with in-process traffic
        self._next_rid = (max(router.tracked) + 1) if router.tracked else 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sheds = 0            # slow-consumer sheds (cumulative)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="frontdoor-router", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            with self.lock:
                busy = not self.router.idle
                if busy:
                    self.router.step()
                self._publish_locked()
            if not busy:
                # fleet router: decode runs in worker processes — park
                # on their push-stream fds instead of spinning step()
                # hot (a spinning parent preempts the workers on small
                # boxes). In-process routers decode INSIDE step(), so
                # their loop must never sleep while busy; they expose
                # no stream fds and skip this entirely.
                fds = self._stream_fds()
                if fds:
                    try:
                        select.select(fds, [], [], 0.002)
                    except (OSError, ValueError):
                        pass  # a stream died mid-select: step resyncs
            else:
                # idle fleet: don't spin the lock on a 1-core box
                self._stop.wait(self.idle_sleep_s)

    def _stream_fds(self) -> List[int]:
        fds = []
        for h in getattr(self.router, "handles", ()):
            fn = getattr(h, "stream_fileno", None)
            fd = fn() if fn is not None else None
            if fd is not None:
                fds.append(fd)
        return fds

    # -------------------------------------------------------------- intake
    def submit(self, fields: dict, tenant: Optional[str], loop, wake
               ) -> Tuple[int, _Subscriber]:
        """Allocate a rid, subscribe, submit — atomically, so the
        subscriber observes every event from seq 0 even when the
        submit itself finalizes at the door (shed/rejected: the stream
        already holds its typed ``end`` when this returns)."""
        with self.lock:
            rid = self._next_rid
            self._next_rid += 1
            sub = _Subscriber(rid, tenant, self.max_buffered_events,
                              loop, wake)
            self._subs[rid] = sub
            self._owned.add(rid)
            self.router.submit(Request(rid=rid, **fields))
            self._publish_locked()
            return rid, sub

    # ------------------------------------------------------------- fan-out
    def _publish_locked(self) -> None:
        for rid, sub in list(self._subs.items()):
            st = self.router.streams.get(rid)
            if st is None or sub.shed:
                continue
            new = st.events[sub.cursor:]
            if not new:
                continue
            sub.cursor = len(st.events)
            if len(sub.events) + len(new) > sub.limit:
                # slow consumer: everything it hasn't read is dropped in
                # one stroke and replaced by a single typed terminal —
                # the request itself keeps decoding (the engine never
                # waits on a socket, so no KV block is pinned by this
                # reader being slow); only delivery is cut short
                sub.events.clear()
                sub.events.append(("end", {"status": "slow_consumer"}))
                sub.shed = True
                self.sheds += 1
            else:
                sub.events.extend(new)
            self._wake(sub)
        # reap orphans: a shed/disconnected reader's request keeps
        # decoding (nothing pins KV on a consumer), so its router-side
        # record can only be dropped once the stream actually closed
        for rid in list(self._owned):
            if rid in self._subs:
                continue
            st = self.router.streams.get(rid)
            tr = self.router.tracked.get(rid)
            if (st is None or st.closed) and (tr is None or tr.done):
                self._owned.discard(rid)
                self.router.streams.pop(rid, None)
                self.router.tracked.pop(rid, None)

    @staticmethod
    def _wake(sub: _Subscriber) -> None:
        try:
            sub.loop.call_soon_threadsafe(sub.wake.set)
        except RuntimeError:
            pass  # connection's loop already closed

    def finish(self, rid: int) -> None:
        """The connection is done with this stream (terminal written,
        or the socket died): unsubscribe, and reap the router-side
        record so a long-lived front door stays bounded. A request
        still decoding (shed reader / dropped socket) is NOT reaped —
        popping its tracked entry would strand `router._pending` and
        the drain floor with it; the publish sweep reaps it when the
        router finalizes."""
        with self.lock:
            self._subs.pop(rid, None)
            tr = self.router.tracked.get(rid)
            st = self.router.streams.get(rid)
            if (tr is None or tr.done) and (st is None or st.closed):
                self._owned.discard(rid)
                self.router.streams.pop(rid, None)
                self.router.tracked.pop(rid, None)

    @property
    def inflight(self) -> int:
        with self.lock:
            return len(self._subs)


class Frontdoor:
    """The HTTP/SSE server. `start()` binds (resolving an ephemeral
    port into `self.port`) and spins the asyncio loop plus the router
    driver on daemon threads; `close()` tears both down. Use as a
    context manager in tests."""

    def __init__(self, router, *, config: FrontdoorConfig = FrontdoorConfig(),
                 admission: Optional[AdmissionController] = None,
                 auth: Optional[Callable[[dict], bool]] = None,
                 validate: Optional[Callable[[dict], Optional[str]]] = None,
                 metrics=None, tracer=None) -> None:
        self.config = config
        self.admission = admission or AdmissionController()
        self._auth = auth
        self._validate = validate
        self.metrics = metrics
        self.tracer = tracer
        self.driver = RouterDriver(
            router, idle_sleep_s=config.idle_sleep_s,
            max_buffered_events=config.max_buffered_events,
        )
        self.port: Optional[int] = None
        self.draining = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._open_conns = 0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Frontdoor":
        self.driver.start()
        self._thread = threading.Thread(
            target=self._serve_thread, name="frontdoor-http", daemon=True
        )
        self._thread.start()
        if not self._ready.wait(timeout=30.0):
            raise RuntimeError("frontdoor failed to bind")
        return self

    def _serve_thread(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def boot():
            self._server = await asyncio.start_server(
                self._handle, self.config.host, self.config.port
            )
            if self.config.sndbuf is not None:
                for s in self._server.sockets:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                                 self.config.sndbuf)
            self.port = self._server.sockets[0].getsockname()[1]
            self._ready.set()

        try:
            loop.run_until_complete(boot())
            loop.run_forever()
        finally:
            try:
                if self._server is not None:
                    self._server.close()
                    loop.run_until_complete(self._server.wait_closed())
            finally:
                loop.close()

    def close(self) -> None:
        if self._loop is not None and not self._loop.is_closed():
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self.driver.stop()

    def __enter__(self) -> "Frontdoor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------------- drain
    def begin_drain(self) -> None:
        """Refuse new generates (503) while in-flight streams finish —
        the same typed-refusal-then-finish shape as the worker's
        SIGTERM path, one layer up."""
        self.draining = True

    def drain(self, timeout_s: float = 30.0) -> bool:
        """begin_drain + block until the floor is clear (True) or the
        timeout lapses with streams still in flight (False)."""
        self.begin_drain()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.driver.inflight == 0 and self.driver.router.idle:
                return True
            time.sleep(0.01)
        return self.driver.inflight == 0 and self.driver.router.idle

    def install_sigterm(self) -> None:
        """SIGTERM -> begin_drain, mirroring serve/worker.py. Main
        thread only (signal module constraint)."""
        signal.signal(signal.SIGTERM, lambda *_: self.begin_drain())

    # ------------------------------------------------------------- metrics
    def _count(self, what: str, **labels) -> None:
        m = self.metrics
        if m is not None:
            m.count(what, **labels)

    def _instant(self, name: str, **attrs) -> None:
        rec = self.tracer
        if rec is not None and getattr(rec, "enabled", False):
            rec.instant(name, pid=ROUTER_PID, **attrs)

    # ------------------------------------------------------------ handler
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._open_conns += 1
        try:
            await self._handle_inner(reader, writer)
        except (ConnectionError, asyncio.IncompleteReadError,
                asyncio.TimeoutError):
            pass  # client went away mid-anything: nothing to answer
        finally:
            self._open_conns -= 1
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _handle_inner(self, reader, writer) -> None:
        try:
            head = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"),
                timeout=self.config.header_timeout_s,
            )
        except asyncio.LimitOverrunError:
            return await self._respond(writer, 431, {"error": "headers too large"})
        method, path, headers = _parse_head(head)
        if method is None:
            return await self._respond(writer, 400, {"error": "malformed request"})
        if method == "GET" and path == "/healthz":
            return await self._healthz(writer)
        if method != "POST" or path != "/v1/generate":
            return await self._respond(writer, 404, {"error": f"no route {method} {path}"})
        # ---- body
        try:
            n = int(headers.get("content-length", ""))
        except ValueError:
            return await self._respond(writer, 411, {"error": "content-length required"})
        if n > self.config.max_body_bytes:
            return await self._respond(writer, 413, {"error": "body too large"})
        body = await reader.readexactly(n)
        # ---- auth (hook wins; else static bearer token when configured)
        if not self._authorized(headers):
            self._count("http", code=401)
            return await self._respond(writer, 401, {"error": "unauthorized"})
        # ---- validation
        try:
            req = json.loads(body)
        except ValueError:
            self._count("http", code=400)
            return await self._respond(writer, 400, {"error": "body is not JSON"})
        err = self._validate_request(req)
        if err is not None:
            self._count("http", code=400)
            return await self._respond(writer, 400, {"error": err})
        # ---- drain gate: typed refusal, retryable elsewhere
        if self.draining:
            self._count("http", code=503)
            return await self._respond(
                writer, 503, {"error": "draining"}, retry_after=1)
        # ---- per-tenant admission
        tenant = req.get("tenant")
        ok, why = self.admission.try_acquire(tenant)
        if not ok:
            self._count("http", code=429)
            self._count("admission_refused", reason=why)
            return await self._respond(
                writer, 429,
                {"error": "admission refused", "reason": why,
                 "tenant": tenant},
                retry_after=1)
        try:
            await self._stream_generate(writer, req, tenant)
        finally:
            self.admission.release(tenant)

    # ------------------------------------------------- the streaming path
    async def _stream_generate(self, writer, req: dict,
                               tenant: Optional[str]) -> None:
        loop = asyncio.get_running_loop()
        wake = asyncio.Event()
        fields = dict(
            prompt=[int(t) for t in req["prompt"]],
            max_new_tokens=int(req.get("max_new_tokens", 32)),
            seed=int(req.get("seed", 0)),
            priority=int(req.get("priority", 0)),
            tenant=tenant,
            temperature=_opt_float(req.get("temperature")),
            top_k=_opt_int(req.get("top_k")),
            top_p=_opt_float(req.get("top_p")),
        )
        if req.get("timeout_s") is not None:
            dl = float(req["timeout_s"])
            with self.driver.lock:
                fields["deadline"] = self.driver.router.clock.now() + dl
        rid, sub = self.driver.submit(fields, tenant, loop, wake)
        # the backpressure trip point: drain() parks once this much is
        # queued in the transport (beyond whatever the kernel absorbs)
        writer.transport.set_write_buffer_limits(
            high=self.config.write_buffer_bytes)
        self._count("http", code=200)
        self._instant("http_request", rid=rid,
                      tenant=tenant or "", n_prompt=len(fields["prompt"]))
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Connection: close\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
        )
        # wire ids are assigned by delivery order here (== StreamEvent
        # seq whenever nothing was shed): contiguity on the wire is a
        # construction, not a hope, and the synthetic slow_consumer
        # terminal slots in without a gap
        next_id = 0
        try:
            while True:
                with self.driver.lock:
                    batch = list(sub.events)
                    sub.events.clear()
                if not batch:
                    await wake.wait()
                    wake.clear()
                    continue
                done = False
                out = bytearray()
                for ev in batch:
                    if isinstance(ev, tuple):   # synthetic (shed) frame
                        kind, data = ev
                    else:
                        kind = ev.kind
                        data = {"start": ev.start,
                                "tokens": list(ev.tokens)}
                        if ev.status is not None:
                            data["status"] = ev.status
                        if ev.attrs:
                            data["attrs"] = ev.attrs
                    out += _chunk(encode_event(kind, next_id, data))
                    next_id += 1
                    if kind == "end":
                        done = True
                if done:
                    out += b"0\r\n\r\n"   # terminating chunk
                writer.write(bytes(out))
                # REAL backpressure: a slow socket parks us here while
                # the driver keeps filling (and, past the bound,
                # shedding) the subscriber buffer
                await writer.drain()
                if done:
                    self._instant("http_stream_end", rid=rid,
                                  frames=next_id)
                    return
        finally:
            self.driver.finish(rid)

    # ------------------------------------------------------------- helpers
    def _authorized(self, headers: dict) -> bool:
        if self._auth is not None:
            return bool(self._auth(headers))
        tok = self.config.auth_token
        if tok is None:
            return True
        return headers.get("authorization", "") == f"Bearer {tok}"

    def _validate_request(self, req) -> Optional[str]:
        if not isinstance(req, dict):
            return "body must be a JSON object"
        prompt = req.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) and t >= 0 for t in prompt)):
            return "prompt must be a non-empty list of token ids"
        if len(prompt) > self.config.max_prompt_len:
            return (f"prompt too long ({len(prompt)} > "
                    f"{self.config.max_prompt_len})")
        mnt = req.get("max_new_tokens", 32)
        if not isinstance(mnt, int) or not (
                1 <= mnt <= self.config.max_new_tokens):
            return (f"max_new_tokens must be an int in "
                    f"[1, {self.config.max_new_tokens}]")
        for key, typ in (("temperature", (int, float)),
                         ("top_p", (int, float)), ("top_k", int),
                         ("seed", int), ("priority", int)):
            v = req.get(key)
            if v is not None and (not isinstance(v, typ)
                                  or isinstance(v, bool)):
                return f"{key} must be a number"
        if self._validate is not None:
            return self._validate(req)
        return None

    async def _healthz(self, writer) -> None:
        with self.driver.lock:
            r = self.driver.router
            body = {
                "status": "draining" if self.draining else "ok",
                "pending": r._pending,
                "inflight_streams": self.driver.inflight,
                "replicas": r.states(),
                "slow_consumer_sheds": self.driver.sheds,
            }
        await self._respond(writer, 200, body)

    @staticmethod
    async def _respond(writer, code: int, body: dict,
                       retry_after: Optional[int] = None) -> None:
        reason = {200: "OK", 400: "Bad Request", 401: "Unauthorized",
                  404: "Not Found", 411: "Length Required",
                  413: "Payload Too Large", 429: "Too Many Requests",
                  431: "Headers Too Large", 503: "Service Unavailable",
                  }.get(code, "Error")
        payload = json.dumps(body).encode()
        head = (f"HTTP/1.1 {code} {reason}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n"
                + (f"Retry-After: {retry_after}\r\n" if retry_after else "")
                + "Connection: close\r\n\r\n")
        writer.write(head.encode() + payload)
        await writer.drain()


# -------------------------------------------------------------- wire parse
def _parse_head(head: bytes):
    try:
        lines = head.decode("latin-1").split("\r\n")
        method, path, _version = lines[0].split(" ", 2)
    except (UnicodeDecodeError, ValueError):
        return None, None, {}
    headers = {}
    for line in lines[1:]:
        if ":" in line:
            k, v = line.split(":", 1)
            headers[k.strip().lower()] = v.strip()
    return method, path, headers


def _chunk(payload: bytes) -> bytes:
    return f"{len(payload):x}\r\n".encode() + payload + b"\r\n"


def _opt_float(v):
    return None if v is None else float(v)


def _opt_int(v):
    return None if v is None else int(v)


# ------------------------------------------------------------- the client
def sse_request(host: str, port: int, body: dict, *,
                headers: Optional[dict] = None,
                timeout_s: float = 60.0,
                read_delay_s: float = 0.0,
                rcvbuf: Optional[int] = None,
                ) -> Tuple[int, List[dict]]:
    """Blocking SSE client over a raw socket: POST the JSON body, parse
    the response, return ``(status_code, events)``. Non-200 responses
    return the JSON error payload as a single ``{"event": "http_error",
    "data": ...}`` pseudo-event so callers always get a typed answer.

    `read_delay_s` sleeps between socket reads and `rcvbuf` shrinks the
    client's receive window (set before connect, so it negotiates) —
    the levers the bench's slow-consumer arm uses to provoke the shed
    path with a genuinely slow reader rather than a mocked one. Shares
    serve/sse.py's parser with the server's encoder, so both ends of
    the wire are pinned to one codec.

    Each returned event carries a ``"t"`` monotonic receive timestamp
    (stamped when its frame was parsed off the socket) so callers can
    score client-side TTFT/inter-token latency without wrapping the
    read loop."""
    payload = json.dumps(body).encode()
    req_headers = {"Host": f"{host}:{port}",
                   "Content-Type": "application/json",
                   "Content-Length": str(len(payload)),
                   "Connection": "close"}
    req_headers.update(headers or {})
    head = "POST /v1/generate HTTP/1.1\r\n" + "".join(
        f"{k}: {v}\r\n" for k, v in req_headers.items()) + "\r\n"
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        if rcvbuf is not None:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
        s.settimeout(timeout_s)
        s.connect((host, port))
        s.sendall(head.encode() + payload)
        raw = b""
        while b"\r\n\r\n" not in raw:
            got = s.recv(65536)
            if not got:
                return 0, []
            raw += got
        head_raw, rest = raw.split(b"\r\n\r\n", 1)
        status = int(head_raw.split(b" ", 2)[1])
        head_text = head_raw.decode("latin-1").lower()
        if status != 200 or "text/event-stream" not in head_text:
            while True:
                got = s.recv(65536)
                if not got:
                    break
                rest += got
            try:
                data = json.loads(rest.decode("utf-8", "replace")
                                  .split("\r\n")[-1] or "{}")
            except ValueError:
                data = {}
            return status, [{"id": None, "event": "http_error",
                             "data": data}]
        parser = SSEParser()
        events: List[dict] = []
        dechunk = _Dechunker()

        def take(data: bytes) -> None:
            new = parser.feed(dechunk.feed(data))
            now = time.monotonic()
            for ev in new:
                ev["t"] = now
            events.extend(new)

        take(rest)
        while not dechunk.done:
            if read_delay_s:
                time.sleep(read_delay_s)
            got = s.recv(512 if read_delay_s else 65536)
            if not got:
                break
            take(got)
        return status, events


class _Dechunker:
    """Minimal HTTP/1.1 chunked-transfer decoder for the client side."""

    def __init__(self) -> None:
        self._buf = b""
        self.done = False

    def feed(self, data: bytes) -> bytes:
        self._buf += data
        out = b""
        while True:
            nl = self._buf.find(b"\r\n")
            if nl < 0:
                return out
            try:
                size = int(self._buf[:nl], 16)
            except ValueError:
                # not at a chunk boundary somehow — surface raw to fail
                # loudly in the parser rather than hang silently
                out += self._buf
                self._buf = b""
                return out
            if len(self._buf) < nl + 2 + size + 2:
                return out
            out += self._buf[nl + 2:nl + 2 + size]
            self._buf = self._buf[nl + 2 + size + 2:]
            if size == 0:
                self.done = True
                return out
