"""Per-replica health: HEALTHY/DEGRADED/DEAD with a circuit breaker.

The serving mirror of train/elastic.py's fail-fast stance. A replica in
a fleet can fail three ways with three different right answers:

- a single bad completion (non-finite logits, injected admission
  failure) — keep routing to it but PREFER its peers (DEGRADED): one
  NaN is a request problem until it repeats;
- repeated consecutive failures — stop routing entirely (DEAD, breaker
  OPEN): the replica is burning requests, and every one routed there is
  a user-visible retry;
- a crash / hung dispatch — instant DEAD: there is nothing to degrade
  to, the in-flight work must migrate NOW (serve/router.py failover).

Recovery is half-open probing: after an exponentially-backed-off wait
(utils/backoff.py — the same helper the restart driver and the router's
retry budget use) the router asks the replica whether it is reachable
again; one successful probe closes the breaker, a failed probe doubles
the wait. Time is injected (the scheduler's clock domain), so breaker
timelines replay deterministically under FakeClock in the chaos tests.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from ddp_practice_tpu.utils.backoff import backoff_delay


class HealthState(enum.Enum):
    HEALTHY = "healthy"
    DEGRADED = "degraded"
    DEAD = "dead"


@dataclasses.dataclass(frozen=True)
class BreakerConfig:
    # consecutive failures that trip the breaker (a crash trips instantly
    # regardless — see ReplicaHealth.mark_dead)
    trip_after: int = 3
    # half-open probe schedule: probe_base_s, then *factor per failed
    # probe, capped at probe_max_s; jittered per (seed, attempt)
    probe_base_s: float = 0.05
    probe_factor: float = 2.0
    probe_max_s: float = 5.0
    probe_jitter: float = 0.0
    seed: int = 0


class CircuitBreaker:
    """Consecutive-failure trip + exponential-backoff half-open probe.

    Pure host-side state machine in an injected clock domain: callers
    pass `now` explicitly (the router owns the clock), nothing here
    reads wall time.
    """

    def __init__(self, config: BreakerConfig = BreakerConfig()) -> None:
        self.config = config
        self.consecutive_failures = 0
        self.open = False
        self.probe_attempts = 0      # failed probes since the trip
        self.next_probe_at: Optional[float] = None
        self.trips = 0               # lifetime trip count (metrics)

    def _schedule_probe(self, now: float) -> None:
        c = self.config
        self.next_probe_at = now + backoff_delay(
            self.probe_attempts, base_s=c.probe_base_s,
            factor=c.probe_factor, max_s=c.probe_max_s,
            jitter=c.probe_jitter, seed=c.seed,
        )

    def record_success(self) -> None:
        self.consecutive_failures = 0

    def record_failure(self, now: float) -> bool:
        """Count one failure; returns True when this one trips the
        breaker (caller migrates in-flight work exactly once)."""
        self.consecutive_failures += 1
        if not self.open and (
            self.consecutive_failures >= self.config.trip_after
        ):
            self.trip(now)
            return True
        return False

    def trip(self, now: float) -> None:
        """Open immediately (crash path) and schedule the first probe."""
        self.open = True
        self.probe_attempts = 0
        self.trips += 1
        self._schedule_probe(now)

    def probe_due(self, now: float) -> bool:
        return self.open and self.next_probe_at is not None \
            and now >= self.next_probe_at

    def on_probe(self, ok: bool, now: float) -> None:
        """Half-open verdict: one good probe closes; a bad one doubles
        the wait (backoff attempt count advances)."""
        if ok:
            self.open = False
            self.consecutive_failures = 0
            self.probe_attempts = 0
            self.next_probe_at = None
        else:
            self.probe_attempts += 1
            self._schedule_probe(now)


class ReplicaHealth:
    """The router's view of one replica: breaker + three-state summary."""

    def __init__(self, config: BreakerConfig = BreakerConfig()) -> None:
        self.breaker = CircuitBreaker(config)

    @property
    def state(self) -> HealthState:
        if self.breaker.open:
            return HealthState.DEAD
        if self.breaker.consecutive_failures > 0:
            return HealthState.DEGRADED
        return HealthState.HEALTHY

    @property
    def alive(self) -> bool:
        return not self.breaker.open

    def mark_success(self) -> None:
        self.breaker.record_success()

    def mark_failure(self, now: float) -> bool:
        """One error-ish event (bad completion, failed admit). True when
        the breaker just tripped — the replica is now DEAD."""
        return self.breaker.record_failure(now)

    def mark_dead(self, now: float) -> None:
        """Crash: skip the consecutive-failure count, trip instantly."""
        if not self.breaker.open:
            self.breaker.trip(now)

    def probe_due(self, now: float) -> bool:
        return self.breaker.probe_due(now)

    def on_probe(self, ok: bool, now: float) -> None:
        self.breaker.on_probe(ok, now)
