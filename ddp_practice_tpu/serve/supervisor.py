"""Supervisor: worker-process lifecycles for the cross-process fleet.

The in-process router's "restart" was a lie a real fleet can't tell:
`ReplicaHandle.restart()` reused the same Python objects, so every
recovery the chaos suite proved was a simulated one. This module owns
REAL lifecycles:

- **spawn**: `python -m ddp_practice_tpu.serve.worker --spec @file` with
  stdout routed to a log file; the supervisor tails the log for the
  ``WORKER_READY`` line (ports + pid), connects the RPC client, and
  health-probes it — a worker is only ever visible to dispatch warm and
  answering.
- **liveness**: `poll()` waitpid-checks every child (a SIGKILLed worker
  is seen the tick after it dies) — heartbeat staleness (the SIGSTOP
  case: alive but silent) is judged by the RemoteReplicaHandle, which
  owns the RPC cadence and puts the zombie down with a real SIGKILL
  before failing over.
- **restart with backoff + budget**: a dead slot respawns after
  utils/backoff.py delays (exponential, capped, per-slot seeded); after
  `restart_budget` restarts the slot's circuit breaks to FAILED — a
  crash-looping replica must page an operator, not burn CPU forever.
  Respawns run on a background thread: a surviving fleet keeps serving
  through a ~15 s jax-import+compile, it does not stop to watch.
- **graceful drain on stop()**: RPC ``shutdown`` first, then SIGTERM,
  then SIGKILL, then ALWAYS waitpid — no test run ever leaks a child.

Every spawned pid is registered in a module-level table with an atexit
reaper (`reap_all`), and tests add a session-scoped fixture on top
(tests/conftest.py) so even a SIGSTOPped orphan cannot outlive — or
hang — a pytest run.

`RemoteReplicaHandle` is the router-facing half: the same narrow
replica interface as serve/router.py's in-process ReplicaHandle
(`submit`/`step`/`poll`/`evacuate`/`shed_queued` + observables), spoken
over serve/rpc.py. Its `step()` is the heartbeat: one watermark poll
that also refreshes the SALVAGE POINT — each outstanding request's
tokens-so-far — so a later SIGKILL re-admits prompt+tokens on a
survivor exactly like the PR-2 in-process failover (token-identical
under greedy, original trace_id preserved).
"""

from __future__ import annotations

import atexit
import dataclasses
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from ddp_practice_tpu.serve.faults import ReplicaCrashed
from ddp_practice_tpu.serve.health import ReplicaHealth
from ddp_practice_tpu.serve.rpc import (
    RpcClient,
    RpcError,
    RpcRemoteError,
    open_stream,
)
from ddp_practice_tpu.serve.scheduler import (
    Completion,
    MonotonicClock,
    Request,
    TokenChunk,
)
from ddp_practice_tpu.serve.worker import READY_PREFIX, WorkerSpec
from ddp_practice_tpu.utils.backoff import backoff_delay

# ------------------------------------------------------------ pid registry
# every child this module ever spawns, alive until explicitly reaped —
# the belt under the supervisor's own bookkeeping. tests/conftest.py's
# session fixture asserts this drains; atexit is the suspenders.
_CHILDREN: Dict[int, subprocess.Popen] = {}
_CHILDREN_LOCK = threading.Lock()


def _register_child(proc: subprocess.Popen) -> None:
    with _CHILDREN_LOCK:
        _CHILDREN[proc.pid] = proc


def _unregister_child(pid: int) -> None:
    with _CHILDREN_LOCK:
        _CHILDREN.pop(pid, None)


def live_worker_pids() -> List[int]:
    """Registered children still running (reaped ones drop out)."""
    with _CHILDREN_LOCK:
        procs = list(_CHILDREN.values())
    return [p.pid for p in procs if p.poll() is None]


def reap_all() -> List[int]:
    """SIGKILL + waitpid every still-live registered child; returns the
    pids that were alive (= leaked — a clean run returns []). SIGKILL
    works on SIGSTOPped processes too, which is the whole point: a
    stopped orphan would otherwise hang any wait()er forever."""
    with _CHILDREN_LOCK:
        procs = list(_CHILDREN.values())
    leaked = []
    for p in procs:
        if p.poll() is None:
            leaked.append(p.pid)
            try:
                p.kill()
            except OSError:
                pass
            try:
                p.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
        _unregister_child(p.pid)
    return leaked


atexit.register(reap_all)


# ---------------------------------------------------------------- spawning
class SpawnedWorker:
    """One live worker process attempt: Popen + ready info + RPC client."""

    def __init__(self, proc: subprocess.Popen, ready: dict,
                 client: RpcClient, log_path: str,
                 spec_path: str) -> None:
        self.proc = proc
        self.pid = proc.pid
        self.rpc_port = ready["rpc_port"]
        self.telemetry_port = ready["telemetry_port"]
        self.client = client
        self.log_path = log_path
        self._spec_path = spec_path

    def poll(self) -> Optional[int]:
        """None while running, else the exit code (waitpid, WNOHANG)."""
        return self.proc.poll()

    def kill_signal(self, sig: str) -> None:
        os.kill(self.pid, getattr(signal, sig))

    def reap(self, timeout_s: float = 5.0) -> None:
        """Ensure the process is collected and the registry is clean."""
        self.client.close()
        if self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass
        try:
            self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            pass
        _unregister_child(self.pid)
        try:
            os.unlink(self._spec_path)
        except OSError:
            pass


def spawn_worker(spec: WorkerSpec, *, log_dir: Optional[str] = None,
                 ready_timeout_s: float = 300.0,
                 rpc_timeout_s: float = 5.0) -> SpawnedWorker:
    """Spawn one worker process and block until it is READY and
    answering pings (raises RuntimeError with the log tail otherwise).
    stdout/stderr go to a LOG FILE, not a pipe — a chatty worker can
    never deadlock against a parent that stopped reading."""
    log_dir = log_dir or tempfile.mkdtemp(prefix="ddp_worker_")
    os.makedirs(log_dir, exist_ok=True)
    fd, spec_path = tempfile.mkstemp(
        suffix=".json", prefix=f"spec_r{spec.replica}_", dir=log_dir
    )
    with os.fdopen(fd, "w") as f:
        f.write(spec.to_json())
    log_path = os.path.join(
        log_dir, f"worker_r{spec.replica}_{int(time.time()*1e3)}.log"
    )
    log_fh = open(log_path, "wb")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "ddp_practice_tpu.serve.worker",
             "--spec", "@" + spec_path],
            stdout=log_fh, stderr=subprocess.STDOUT,
        )
    finally:
        log_fh.close()  # the child holds its own descriptor
    _register_child(proc)
    ready = None
    deadline = time.monotonic() + ready_timeout_s
    while time.monotonic() < deadline:
        try:
            with open(log_path, errors="replace") as f:
                for line in f:
                    if line.startswith(READY_PREFIX):
                        ready = json.loads(line[len(READY_PREFIX):])
                        break
        except OSError:
            pass
        if ready is not None:
            break
        if proc.poll() is not None:
            break
        time.sleep(0.1)
    if ready is None:
        rc = proc.poll()
        tail = ""
        try:
            with open(log_path, errors="replace") as f:
                tail = f.read()[-2000:]
        except OSError:
            pass
        # never leave a half-booted child behind
        try:
            proc.kill()
        except OSError:
            pass
        try:
            proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            pass
        _unregister_child(proc.pid)
        raise RuntimeError(
            f"worker {spec.replica} never became ready "
            f"(rc={rc}); log tail:\n{tail}"
        )
    client = RpcClient("127.0.0.1", ready["rpc_port"],
                       timeout_s=rpc_timeout_s, seed=spec.replica)
    # the health probe: ready AND answering before anyone dispatches
    client.call("ping", timeout_s=rpc_timeout_s)
    return SpawnedWorker(proc, ready, client, log_path, spec_path)


# --------------------------------------------------------------- supervisor
@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    # restart backoff schedule (per slot, utils/backoff.py)
    restart_base_s: float = 0.25
    restart_factor: float = 2.0
    restart_max_s: float = 10.0
    restart_jitter: float = 0.0
    seed: int = 0
    # restart-budget circuit breaker: after this many restarts a slot
    # goes FAILED for good (operator territory — a crash loop must not
    # burn the machine forever). Counts spawn FAILURES too.
    restart_budget: int = 5
    # rolling window for the budget: None = lifetime count (FAILED is
    # permanent until revive(slot)); a float makes the budget count
    # only restarts within the last window — a slot that exhausted its
    # budget during a transient storm rejoins once the storm ages out
    restart_window_s: Optional[float] = None
    # how long a spawn may take to reach READY (jax import + compile)
    ready_timeout_s: float = 300.0
    rpc_timeout_s: float = 5.0
    # stop(): how long to wait after a graceful rpc shutdown before
    # escalating to SIGTERM, then SIGKILL
    drain_timeout_s: float = 5.0
    # shrink(): how long a DRAINING slot may take to finish its
    # in-flight streams and exit before poll() escalates to SIGKILL
    # (a drain that never converges is a hang, not a graceful exit)
    shrink_kill_after_s: float = 60.0


# slot states
RUNNING = "running"
BACKOFF = "backoff"      # dead, respawn scheduled at _next_at
SPAWNING = "spawning"    # respawn in flight on the spawn thread
FAILED = "failed"        # restart budget exhausted — breaker open
STOPPED = "stopped"
DRAINING = "draining"    # scale-down in flight: refusing submits,
#                          finishing streams, exiting on its own — a
#                          death here is RETIREMENT, never a respawn


class Supervisor:
    """Owns N worker slots: spawn, liveness, backoff restarts, drain.

    `spawn_fn(spec)` is injectable (defaults to `spawn_worker`) so the
    restart state machine is host-pure testable with fakes;
    `spawn_in_thread=False` makes respawns synchronous inside `poll()`
    for deterministic tests (the default keeps the fleet serving while
    a replacement compiles)."""

    def __init__(self, specs: List[WorkerSpec],
                 config: SupervisorConfig = SupervisorConfig(), *,
                 spawn_fn: Optional[Callable] = None,
                 spawn_in_thread: bool = True,
                 clock=None) -> None:
        self.specs = list(specs)
        self.config = config
        self.spawn_fn = spawn_fn or self._default_spawn
        self.spawn_in_thread = spawn_in_thread
        self.clock = clock or MonotonicClock()
        self._log_dir = None  # lazily created by _default_spawn
        n = len(specs)
        self.workers: List[Optional[object]] = [None] * n
        self.states: List[str] = [STOPPED] * n
        self.restarts: List[int] = [0] * n    # lifetime restarts/slot
        # budget accounting, separate from the lifetime telemetry
        # counter above: revive() zeroes THESE, never the telemetry
        self._budget_used: List[int] = [0] * n
        self._restart_times: List[List[float]] = [[] for _ in range(n)]
        self._next_at: List[float] = [0.0] * n
        self._spawn_threads: List[Optional[threading.Thread]] = [None] * n
        self._spawn_results: List[Optional[tuple]] = [None] * n
        # scale-down bookkeeping: SIGKILL deadline per DRAINING slot,
        # and a cancel flag a shrink() of a SPAWNING slot leaves for
        # _collect_spawn (the fresh worker is reaped, never joined)
        self._drain_deadline: List[Optional[float]] = [None] * n
        self._cancel_spawn: List[bool] = [False] * n
        self._lock = threading.Lock()

    def _default_spawn(self, spec: WorkerSpec):
        if self._log_dir is None:
            self._log_dir = tempfile.mkdtemp(prefix="ddp_fleet_")
        return spawn_worker(
            spec, log_dir=self._log_dir,
            ready_timeout_s=self.config.ready_timeout_s,
            rpc_timeout_s=self.config.rpc_timeout_s,
        )

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Spawn every slot, synchronously (first boot is setup, not
        serving — the fleet exists only once all replicas are warm)."""
        for slot in range(len(self.specs)):
            self.workers[slot] = self.spawn_fn(self.specs[slot])
            self.states[slot] = RUNNING

    def worker(self, slot: int):
        """The slot's CURRENT process (None while down) — callers must
        re-resolve per use; a restarted slot has a new pid/client. A
        DRAINING worker is still a live process (its handle keeps
        pumping completions out of it) — only dispatch eligibility is
        gone, and that is `alive()`'s job, not this one's."""
        if self.states[slot] in (RUNNING, DRAINING):
            return self.workers[slot]
        return None

    def alive(self, slot: int) -> bool:
        return self.states[slot] == RUNNING

    def draining(self, slot: int) -> bool:
        return self.states[slot] == DRAINING

    def state(self, slot: int) -> str:
        return self.states[slot]

    def active_slots(self) -> int:
        """Slots that serve or will serve again (RUNNING + the restart
        pipeline) — the autoscaler's notion of fleet size. DRAINING
        slots are already leaving; STOPPED/FAILED are gone."""
        return sum(
            1 for s in self.states if s in (RUNNING, BACKOFF, SPAWNING)
        )

    def kill(self, slot: int, sig: str = "SIGKILL") -> None:
        """Deliver a REAL signal to the slot's current process (the
        chaos driver's kill_fn, and the handle's stale-heartbeat
        put-down). No-op when the slot is already down."""
        if not 0 <= slot < len(self.workers):
            raise ValueError(
                f"kill targets slot {slot}; this fleet has "
                f"{len(self.workers)} (a kill plan naming a replica "
                f"the fleet doesn't have is a plan bug)"
            )
        w = self.workers[slot]
        if w is not None and w.poll() is None:
            w.kill_signal(sig)

    # ----------------------------------------------- elastic actuators
    def grow(self, spec: WorkerSpec, worker=None) -> int:
        """Append a NEW slot and return its id. Slot ids are stable and
        monotonically increasing: a shrunk slot becomes a STOPPED
        tombstone, never a hole, so every federated label minted for a
        slot stays true across scale events. With `worker` (a warm
        standby) the slot joins RUNNING immediately — promotion is a
        list append, not a ~15 s spawn; without one the slot enters
        BACKOFF due NOW and the next poll() spawns it cold through the
        normal (budget-free first) pipeline."""
        with self._lock:
            slot = len(self.specs)
            self.specs.append(spec)
            self.workers.append(worker)
            self.states.append(RUNNING if worker is not None else BACKOFF)
            self.restarts.append(0)
            self._budget_used.append(0)
            self._restart_times.append([])
            self._next_at.append(self.clock.now())
            self._spawn_threads.append(None)
            self._spawn_results.append(None)
            self._drain_deadline.append(None)
            self._cancel_spawn.append(False)
            return slot

    def shrink(self, slot: int) -> str:
        """Scale one slot away, gracefully; returns the slot's state
        after the call. A RUNNING slot drains via the PR-9 SIGTERM path
        (rpc `drain` first so refusals start even if signal delivery
        lags): it refuses new submits, finishes its in-flight streams,
        and exits on its own — poll() then retires it to STOPPED with
        NO restart-budget charge and NO respawn. A BACKOFF slot's
        pending respawn is cancelled outright; a SPAWNING slot's
        in-flight attempt is flagged for _collect_spawn to reap.
        Intentional scale-down is not a crash: none of these touch
        `restarts`, `_budget_used`, or the rolling window."""
        if not 0 <= slot < len(self.specs):
            raise ValueError(
                f"shrink targets slot {slot}; this fleet has "
                f"{len(self.specs)}"
            )
        now = self.clock.now()
        with self._lock:
            st = self.states[slot]
            if st == RUNNING:
                w = self.workers[slot]
                if w is not None and w.poll() is None:
                    try:
                        w.client.call("drain", timeout_s=1.0, retries=0)
                    except (RpcError, RpcRemoteError):
                        pass  # SIGTERM below carries the same intent
                    try:
                        w.kill_signal("SIGTERM")
                    except OSError:
                        pass
                    self.states[slot] = DRAINING
                    self._drain_deadline[slot] = (
                        now + self.config.shrink_kill_after_s
                    )
                else:
                    # already a corpse: collect it without the budget
                    # charge a poll()-observed death would levy
                    if w is not None:
                        w.reap()
                    self.workers[slot] = None
                    self.states[slot] = STOPPED
            elif st == BACKOFF:
                self.states[slot] = STOPPED
            elif st == SPAWNING:
                self._cancel_spawn[slot] = True
            elif st == FAILED:
                self.states[slot] = STOPPED
            return self.states[slot]

    # ------------------------------------------------------ the state loop
    def poll(self, now: Optional[float] = None) -> None:
        """One liveness pass: waitpid every RUNNING slot (dead ->
        schedule restart with backoff, or FAILED past the budget),
        launch due respawns, collect finished spawn attempts."""
        now = self.clock.now() if now is None else now
        with self._lock:
            for slot in range(len(self.specs)):
                st = self.states[slot]
                if st == RUNNING:
                    w = self.workers[slot]
                    if w is None or w.poll() is not None:
                        self._on_death(slot, now)
                elif st == DRAINING:
                    w = self.workers[slot]
                    if w is None or w.poll() is not None:
                        # drained clean (exit 0) or chaos-killed
                        # mid-drain: either way the slot RETIRES —
                        # an intentional scale-down is not a crash,
                        # so no budget charge and no respawn
                        if w is not None:
                            w.reap()
                        self.workers[slot] = None
                        self.states[slot] = STOPPED
                        self._drain_deadline[slot] = None
                    elif (self._drain_deadline[slot] is not None
                          and now >= self._drain_deadline[slot]):
                        # the drain never converged: put it down for
                        # real (the handle already salvaged its work)
                        try:
                            w.kill_signal("SIGKILL")
                        except OSError:
                            pass
                        self._drain_deadline[slot] = None
                elif st == BACKOFF and now >= self._next_at[slot]:
                    self._begin_spawn(slot, now)
                elif st == SPAWNING:
                    self._collect_spawn(slot, now)
                elif st == FAILED \
                        and self.config.restart_window_s is not None \
                        and self._budget_spent(slot, now) \
                        < self.config.restart_budget:
                    # the crash storm aged out of the rolling window:
                    # the breaker half-closes and the slot rejoins
                    self._next_at[slot] = now
                    self.states[slot] = BACKOFF

    def _budget_spent(self, slot: int, now: float) -> int:
        """Restarts counting against the budget: the lifetime count by
        default, only those inside the rolling window when one is
        configured (pruning as a side effect — old entries never count
        again)."""
        w = self.config.restart_window_s
        if w is None:
            return self._budget_used[slot]
        times = self._restart_times[slot]
        times[:] = [t for t in times if now - t < w]
        return len(times)

    def revive(self, slot: int) -> None:
        """Operator escape hatch: put a FAILED slot back in play NOW,
        with a fresh budget (a revive that instantly re-tripped would
        be no escape at all). Lifetime restart telemetry is preserved."""
        if self.states[slot] != FAILED:
            return
        with self._lock:
            self._budget_used[slot] = 0
            self._restart_times[slot] = []
            self._next_at[slot] = self.clock.now()
            self.states[slot] = BACKOFF

    def _on_death(self, slot: int, now: float) -> None:
        w = self.workers[slot]
        if w is not None:
            w.reap()
        self.workers[slot] = None
        if self._budget_spent(slot, now) >= self.config.restart_budget:
            # the restart-budget circuit breaker: slot is done (for
            # good without a window — see revive(); until the storm
            # ages out with one — see poll())
            self.states[slot] = FAILED
            return
        c = self.config
        delay = backoff_delay(
            self.restarts[slot], base_s=c.restart_base_s,
            factor=c.restart_factor, max_s=c.restart_max_s,
            jitter=c.restart_jitter, seed=c.seed + slot,
        )
        self.restarts[slot] += 1
        self._budget_used[slot] += 1
        self._restart_times[slot].append(now)
        self._next_at[slot] = now + delay
        self.states[slot] = BACKOFF

    def _begin_spawn(self, slot: int, now: float) -> None:
        self.states[slot] = SPAWNING
        self._spawn_results[slot] = None

        def attempt():
            try:
                self._spawn_results[slot] = ("ok",
                                             self.spawn_fn(self.specs[slot]))
            except BaseException as e:
                self._spawn_results[slot] = ("err", e)

        if self.spawn_in_thread:
            t = threading.Thread(
                target=attempt, name=f"spawn-w{slot}", daemon=True
            )
            t.start()
            self._spawn_threads[slot] = t
        else:
            attempt()
            self._collect_spawn(slot, now)

    def _collect_spawn(self, slot: int, now: float) -> None:
        res = self._spawn_results[slot]
        if res is None:
            return  # still compiling/importing on the spawn thread
        self._spawn_results[slot] = None
        self._spawn_threads[slot] = None
        kind, val = res
        if self._cancel_spawn[slot]:
            # shrink() landed while the spawn was in flight: the slot
            # is being scaled away, so the fresh worker (if the spawn
            # even succeeded) is reaped, and a spawn FAILURE costs no
            # budget — cancellation is intent, not a crash
            self._cancel_spawn[slot] = False
            if kind == "ok":
                val.reap()
            self.workers[slot] = None
            self.states[slot] = STOPPED
            return
        if kind == "ok":
            self.workers[slot] = val
            self.states[slot] = RUNNING
        else:
            # a failed spawn consumes restart budget like a death —
            # a spec that cannot boot must trip the breaker, not spin
            self.states[slot] = RUNNING  # let _on_death do the math
            self.workers[slot] = None
            self._on_death(slot, now)

    # -------------------------------------------------------------- stop
    def stop(self) -> None:
        """Graceful drain: rpc shutdown -> wait -> SIGTERM -> SIGKILL ->
        ALWAYS waitpid. Also joins any in-flight spawn attempt and
        reaps its result, so no child survives a stop() however
        mid-restart it was called."""
        with self._lock:
            for slot, t in enumerate(self._spawn_threads):
                if t is not None:
                    t.join(timeout=self.config.ready_timeout_s)
                    self._collect_spawn(slot, self.clock.now())
            for slot in range(len(self.specs)):
                w = self.workers[slot]
                self.states[slot] = STOPPED
                self.workers[slot] = None
                if w is None:
                    continue
                try:
                    w.client.call("shutdown", timeout_s=2.0, retries=0)
                except (RpcError, RpcRemoteError):
                    pass
                try:
                    w.proc.wait(timeout=self.config.drain_timeout_s)
                except (subprocess.TimeoutExpired, AttributeError):
                    if w.poll() is None:
                        try:
                            w.kill_signal("SIGTERM")
                            w.proc.wait(timeout=2.0)
                        except (subprocess.TimeoutExpired, OSError,
                                AttributeError):
                            pass
                w.reap()

    def __enter__(self) -> "Supervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# ------------------------------------------------------ router-facing handle
_ZERO_PHASES = {"queue_s": 0.0, "prefill_s": 0.0, "decode_s": 0.0}


class RemoteReplicaHandle:
    """serve/router.py's replica interface over the RPC wire.

    `step()` is the heartbeat/watermark poll (fail-fast timeout, no
    transport retries — staleness accounting judges); `submit` rides
    the retry budget (the worker dedups by rid, so a replayed frame is
    safe). Outstanding requests carry their last-polled tokens-so-far:
    `evacuate()` after a real death hands the router the same
    (request, tokens, ftt, phases) tuples the in-process scheduler
    harvest gives, built from the last salvage point instead of a
    scheduler that no longer exists."""

    def __init__(self, slot: int, supervisor: Supervisor,
                 spec: WorkerSpec, *, clock=None,
                 heartbeat_timeout_s: float = 2.0,
                 poll_timeout_s: float = 1.0,
                 poll_interval_s: float = 0.005,
                 trace_collector=None) -> None:
        self.id = slot
        self.supervisor = supervisor
        self.spec = spec
        self.clock = clock or supervisor.clock
        self.health = ReplicaHealth()   # re-armed by the Router
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.poll_timeout_s = poll_timeout_s
        # optional utils/trace.py TraceCollector: `trace` push frames
        # (the worker's streamed spans) merge through it into the fleet
        # recorder, and every timestamped ping/poll round trip feeds its
        # per-worker clock-offset estimator
        self.trace_collector = trace_collector
        # min spacing between heartbeat RPCs: the router ticks as fast
        # as it can, but hammering the worker's lock with a poll per
        # tick steals the very core the decode needs (measured: the
        # unthrottled loop costs the fleet ~25% decode p50 on a 1-core
        # box). Liveness (waitpid) is still checked EVERY step.
        self.poll_interval_s = poll_interval_s
        self._last_poll = -1e18
        self._pub_version = None   # worker snapshot version (poll dedup)
        # push stream (rpc.py FrameStream): the worker pushes every
        # published snapshot; step() drains it without blocking, so
        # steady-state completion delivery costs no round trips. The
        # poll op demotes to a slow reconciliation heartbeat while the
        # stream is up, and is the sole path when it is not.
        self._stream = None
        self.stream_poll_interval_s = 0.25
        self.consumed = 0               # watermark into the CURRENT
        #                                 process's completions list
        self.chunks_consumed = 0        # same contract, TokenChunk list
        self.outstanding: Dict[int, dict] = {}
        # fire-and-forget submits awaiting confirmation: rid -> casts
        # sent. Confirmation is the rid surfacing in a pub/poll frame
        # (completion or inflight salvage) or the reconcile poll's
        # `confirmed` answer; a rid the worker never saw is resubmitted
        # (idempotent by rid), a refused one surfaces as a typed
        # "refused" completion so the router re-dispatches penalty-free
        self._unconfirmed: Dict[int, int] = {}
        self._pending: List[Completion] = []
        self._pending_chunks: List[TokenChunk] = []
        # set when the worker refused a submit as DRAINING (typed, not
        # a fault): the router retries its next candidate instead of
        # writing the replica off; has_queue_space goes False until the
        # stats say otherwise (or the drained process exits)
        self.last_submit_refused = False
        self._remote_draining = False
        # scale-down lifecycle: begin_drain() is stamped by the
        # autoscaler when it shrinks this slot; once the drained
        # process exits with nothing left to salvage, step() sets
        # `drained` and goes quiet instead of raising ReplicaCrashed —
        # a retirement, not a failover
        self._drain_requested = False
        self.drained = False
        # rids shed via shed_queued(): their worker-side sub-completions
        # are already finalized by the router from the op's reply, so
        # when they replay through the push stream / poll they must be
        # DROPPED — the rid may have been legitimately reused by then
        # (the same double-booking the in-process handle's watermark
        # advance prevents)
        self._shed_skip: set = set()
        self._stats: dict = {}
        self._last_heartbeat: Optional[float] = None
        self._broken = False            # rpc failed since last step
        buckets = spec.engine.get("prompt_buckets") or (8, 16, 32, 64)
        self._max_bucket = max(buckets)
        self._max_slots = spec.engine.get("max_slots", 4)
        self._max_queue = spec.max_queue

    # ------------------------------------------------------------ plumbing
    def _client(self) -> Optional[RpcClient]:
        w = self.supervisor.worker(self.id)
        return w.client if w is not None else None

    @staticmethod
    def _request_dict(req: Request) -> dict:
        return {
            "rid": req.rid, "prompt": list(req.prompt),
            "max_new_tokens": req.max_new_tokens,
            "deadline": req.deadline, "seed": req.seed,
            "arrival": req.arrival, "priority": req.priority,
            "trace_id": req.trace_id, "sampled": req.sampled,
            "tenant": req.tenant,
            "temperature": req.temperature, "top_k": req.top_k,
            "top_p": req.top_p,
        }

    @staticmethod
    def _to_completion(d: dict) -> Completion:
        return Completion(
            rid=d["rid"], tokens=list(d["tokens"]), status=d["status"],
            arrival=d["arrival"], finish=d["finish"],
            ttft=d.get("ttft"), tpot=d.get("tpot"),
            flight=d.get("flight"), trace_id=d.get("trace_id"),
            trace_sampled=d.get("sampled", True),
            tenant=d.get("tenant"),
        )

    # ---------------- the seam: submit down, completions watermark up
    def submit(self, req: Request) -> None:
        if req.trace_id is None:
            req.trace_id = f"r{req.rid}"
        self.last_submit_refused = False
        # track BEFORE the wire: if the call fails mid-flight the
        # request is outstanding either way, and evacuate() re-admits
        # it on a survivor (the worker-side dedup absorbs the case
        # where the frame did land)
        self.outstanding[req.rid] = {
            "req": req, "tokens": [], "ftt": None,
            "phases": dict(_ZERO_PHASES),
        }
        c = self._client()
        if c is None:
            self._broken = True
            return
        cast = getattr(c, "cast", None)
        if cast is not None:
            # fire-and-forget: ship the frame, wait for NO ack — the
            # ack round trip was most of the remaining TTFT hop at the
            # RPC seam. The worker dedups by rid, so delivery is
            # confirmed (and re-driven) by the reconcile poll instead:
            # step() asks the worker to `confirm` every unconfirmed
            # rid, resubmits the lost ones, and surfaces a draining
            # refusal as a typed "refused" completion.
            try:
                cast("submit", request=self._request_dict(req))
            except (RpcError, RpcRemoteError):
                self._broken = True
                return
            self._unconfirmed[req.rid] = 1
            return
        # legacy blocking path (test fakes without one-way support)
        try:
            r = c.call("submit", request=self._request_dict(req))
        except (RpcError, RpcRemoteError):
            self._broken = True
            return
        if not r.get("accepted", False):
            if r.get("draining"):
                # graceful-drain refusal (SIGTERM path): the worker is
                # finishing its in-flight streams and will exit — not a
                # fault. Untrack (no completion will ever come from
                # here) and tell the router to try its next candidate.
                self.outstanding.pop(req.rid, None)
                self.last_submit_refused = True
                self._remote_draining = True
                return
            # refused at the door otherwise: the request must not
            # strand in `outstanding` with no completion ever coming
            # — treat like a replica failure, so the next step() raises
            # and the evacuation re-dispatches it on a survivor
            self._broken = True

    def _apply_snapshot(self, *, version, from_wm, completions, upto,
                        inflight, stats, chunks=(), chunks_from=None,
                        chunks_upto=None) -> None:
        """Fold one published worker snapshot (push frame or poll
        reply) into client state. `from_wm` is where the payload's
        completion slice starts — anything below our own watermark is a
        replay (stream/poll overlap) and is skipped, never re-pended.
        The TokenChunk slice rides the same replay-skip contract on its
        own watermark (defaults keep pre-streaming fakes working)."""
        self._pub_version = version
        if upto > self.consumed:
            start = max(0, self.consumed - from_wm)
            for d in completions[start:]:
                self._unconfirmed.pop(d["rid"], None)
                if d["rid"] in self._shed_skip:
                    self._shed_skip.discard(d["rid"])
                    continue  # already finalized from the shed reply
                self._pending.append(self._to_completion(d))
            self.consumed = upto
        if chunks_from is None:
            chunks_from = self.chunks_consumed
        if chunks_upto is None:
            chunks_upto = chunks_from + len(chunks)
        if chunks_upto > self.chunks_consumed:
            start = max(0, self.chunks_consumed - chunks_from)
            for d in chunks[start:]:
                self._pending_chunks.append(TokenChunk(
                    rid=d["rid"], trace_id=d.get("trace_id"),
                    seq=d["seq"], start=d["start"],
                    tokens=list(d["tokens"]), t=d.get("t", 0.0),
                    final=d.get("final", False),
                    status=d.get("status"),
                ))
            self.chunks_consumed = chunks_upto
        for item in inflight:
            self._unconfirmed.pop(item["rid"], None)
            st = self.outstanding.get(item["rid"])
            if st is not None:
                st["tokens"] = list(item["tokens"])
                st["ftt"] = item["ftt"]
                st["phases"] = {
                    k: item["phases"].get(k, 0.0) for k in _ZERO_PHASES
                }
        if stats is not None:
            self._stats = stats
            # drain state rides the stats: a draining worker stops
            # being a dispatch candidate even before its first refusal
            self._remote_draining = bool(stats.get("draining", False))

    def _ensure_stream(self) -> None:
        if self._stream is not None:
            return
        w = self.supervisor.worker(self.id)
        port = getattr(w, "rpc_port", None)  # fakes have no stream plane
        if port is None:
            return
        try:
            self._stream = open_stream(
                "127.0.0.1", port, watermark=self.consumed,
                chunks_watermark=self.chunks_consumed,
                timeout_s=self.poll_timeout_s,
            )
        except (RpcError, RpcRemoteError):
            self._stream = None  # poll path carries on

    def _drop_stream(self) -> None:
        if self._stream is not None:
            self._stream.close()
            self._stream = None

    def _apply_pub_frame(self, f: dict) -> None:
        self._apply_snapshot(
            version=f.get("version"), from_wm=f["from"],
            completions=f["completions"],
            upto=f["watermark"], inflight=f["inflight"],
            stats=f["stats"],
            chunks=f.get("chunks", ()),
            chunks_from=f.get("chunks_from"),
            chunks_upto=f.get("chunks_watermark"),
        )

    def _final_drain(self) -> None:
        """Best-effort drain of a DEAD process's push stream (TCP
        buffers outlive the process): apply any pub frames that made it
        out before the kill, then drop the stream. Completions that
        surface here finalize normally; their rids are excluded from
        the evacuation salvage (see evacuate())."""
        if self._stream is None:
            return
        try:
            while True:
                frames = self._stream.drain()
                if not frames:
                    break
                for f in frames:
                    if f.get("kind") == "pub":
                        self._apply_pub_frame(f)
        except RpcError:
            pass
        self._drop_stream()

    def step(self) -> None:
        """Heartbeat + completion intake + salvage refresh. Fast path:
        drain the push stream (no blocking, no round trips); slow path:
        the poll op, per `poll_interval_s` when the stream is down and
        per `stream_poll_interval_s` as reconciliation when it is up.
        Raises ReplicaCrashed on process death, on a broken submit, or
        when heartbeats stayed stale past the budget (the SIGSTOP case
        — after SIGKILLing the silent process so the supervisor's
        waitpid sees a real corpse and schedules the restart)."""
        now = self.clock.now()
        self.supervisor.poll(now)
        if (not self.supervisor.alive(self.id)
                and not self.supervisor.draining(self.id)):
            # one FINAL stream drain before the failover: frames the
            # kernel buffered before the death survive the process, and
            # the salvage point + chunk slice they carry are fresher
            # than our last applied snapshot — minutes of resume gap
            # become the one burst the frame missed
            self._final_drain()
            if self._drain_requested \
                    and self.supervisor.state(self.id) == STOPPED:
                done = {c.rid for c in self._pending}
                if all(rid in done for rid in self.outstanding):
                    # clean scale-down retirement: every stream this
                    # worker owed is finalized (or pending finalize),
                    # the process exited on its own, nothing to fail
                    # over — the autoscaler reaps the handle
                    self.drained = True
                    return
                # chaos killed the draining worker mid-stream: this IS
                # a failover — the salvage below re-admits the leftovers
            raise ReplicaCrashed(f"worker {self.id}: process down")
        if self._broken:
            self._broken = False
            raise ReplicaCrashed(f"worker {self.id}: rpc failed")
        self._ensure_stream()
        if self._stream is not None:
            try:
                frames = self._stream.drain()
            except RpcError:
                self._drop_stream()
                frames = []
            for f in frames:
                self._last_heartbeat = now
                if f.get("kind") == "pub":
                    self._apply_pub_frame(f)
                elif f.get("kind") == "trace" \
                        and self.trace_collector is not None:
                    # worker spans -> the fleet timeline (the collector
                    # dedups by frame seq and applies the clock offset)
                    self.trace_collector.ingest(self.id, f)
        interval = (self.stream_poll_interval_s
                    if self._stream is not None
                    else self.poll_interval_s)
        if now - self._last_poll < interval:
            return  # stream current / throttled; liveness was checked
        self._last_poll = now
        c = self._client()
        sent_wm = self.consumed
        sent_cwm = self.chunks_consumed
        # reconcile fire-and-forget submits: ask the worker which of
        # the unconfirmed rids it has seen (answered from its dedup
        # map, on the same connection the casts rode)
        asked = list(self._unconfirmed) if self._unconfirmed else None
        extra = {"confirm": asked} if asked else {}
        t0 = self.clock.now()
        try:
            r = c.call("poll", watermark=sent_wm,
                       chunks_watermark=sent_cwm,
                       version=self._pub_version,
                       timeout_s=self.poll_timeout_s, retries=0,
                       **extra)
        except (RpcError, RpcRemoteError):
            hb = self._last_heartbeat
            if hb is None:
                self._last_heartbeat = hb = now
            if now - hb > self.heartbeat_timeout_s:
                # alive by waitpid but silent on the wire: put it down
                # for real so restart machinery takes over
                self.supervisor.kill(self.id, "SIGKILL")
                raise ReplicaCrashed(
                    f"worker {self.id}: heartbeat stale "
                    f"({now - hb:.2f}s)"
                )
            return  # transient blip: skip the tick, keep the salvage
        self._last_heartbeat = now
        self._clock_sample(r, t0, self.clock.now())
        if r.get("unchanged"):
            self._pub_version = r.get("version", self._pub_version)
            if asked:
                self._reconcile_confirm(r.get("confirmed"), asked)
            return  # heartbeat only: salvage/stats still current
        self._apply_snapshot(
            version=r.get("version"), from_wm=sent_wm,
            completions=r["completions"], upto=r["watermark"],
            inflight=r["inflight"], stats=r["stats"],
            chunks=r.get("chunks", ()),
            chunks_from=r.get("chunks_from", sent_cwm),
            chunks_upto=r.get("chunks_watermark"),
        )
        if asked:
            self._reconcile_confirm(r.get("confirmed"), asked)

    def _reconcile_confirm(self, confirmed: Optional[dict],
                           asked: list) -> None:
        """Resolve fire-and-forget submits against the worker's dedup
        answer. True = accepted (confirmed); False = refused at the
        door (draining) — surface a typed "refused" completion so the
        router re-dispatches without burning a retry, the one-way twin
        of `last_submit_refused`; absent = the cast never landed —
        resubmit (idempotent by rid), and after the resubmit budget
        treat the replica as broken so evacuation re-homes the work."""
        if confirmed is None:
            return
        now = self.clock.now()
        for rid in asked:
            if rid not in self._unconfirmed:
                continue  # resolved by a frame in the meantime
            verdict = confirmed.get(str(rid))
            if verdict is True:
                self._unconfirmed.pop(rid, None)
                continue
            if verdict is False:
                self._unconfirmed.pop(rid, None)
                st = self.outstanding.pop(rid, None)
                self._remote_draining = True
                if st is not None:
                    req = st["req"]
                    self._pending.append(Completion(
                        rid=rid, tokens=[], status="refused",
                        arrival=req.arrival, finish=now,
                        ttft=None, tpot=None, flight=None,
                        trace_id=req.trace_id, tenant=req.tenant,
                    ))
                continue
            # never seen by the worker: the one-way frame was lost
            tries = self._unconfirmed.get(rid, 1)
            st = self.outstanding.get(rid)
            if st is None:
                self._unconfirmed.pop(rid, None)
                continue
            if tries >= 3:
                self._unconfirmed.pop(rid, None)
                self._broken = True  # evacuation re-admits it elsewhere
                continue
            c = self._client()
            cast = getattr(c, "cast", None) if c is not None else None
            if cast is None:
                self._unconfirmed.pop(rid, None)
                self._broken = True
                continue
            try:
                cast("submit", request=self._request_dict(st["req"]))
            except (RpcError, RpcRemoteError):
                self._broken = True
                return
            self._unconfirmed[rid] = tries + 1

    def _clock_sample(self, reply: dict, t0: float, t3: float) -> None:
        """Feed one timestamped round trip to the collector's offset
        estimator (every poll/ping reply carries the worker's clock)."""
        if self.trace_collector is None:
            return
        tw = reply.get("t")
        if tw is not None:
            self.trace_collector.add_clock_sample(self.id, t0, tw, t3)

    def measure_clock(self, samples: int = 4) -> Optional[float]:
        """Eagerly sample the worker's clock offset over `samples`
        pings; returns the resulting skew bound (None without a
        collector or a reachable worker). Run against an IDLE fleet
        (fleet build, post-restart probe) the RTT is tens of
        microseconds — far tighter than anything measured mid-decode,
        which is exactly why the eager pass exists: every trace frame
        merged later rides an offset whose error bound was set here."""
        if self.trace_collector is None:
            return None
        c = self._client()
        if c is None:
            return None
        for _ in range(max(1, samples)):
            t0 = self.clock.now()
            try:
                r = c.call("ping", timeout_s=self.poll_timeout_s,
                           retries=0)
            except (RpcError, RpcRemoteError):
                break
            self._clock_sample(r, t0, self.clock.now())
        return self.trace_collector.skew_bound(self.id)

    def set_trace(self, enabled: bool,
                  sample: Optional[float] = None,
                  tenant_rates: Optional[dict] = None) -> bool:
        """Toggle the worker's span recording (the overhead bench's
        on/off lever); `sample` adjusts the worker's head rate in place
        (the sampling bench's per-arm knob, the adaptive controller's
        fleet push), `tenant_rates` replaces its per-tenant override
        table. False when the worker has no tracer or the call failed
        (a disabled plane, not an error)."""
        c = self._client()
        if c is None:
            return False
        try:
            r = c.call("trace", enabled=enabled, sample=sample,
                       tenant_rates=tenant_rates,
                       timeout_s=self.poll_timeout_s)
        except (RpcError, RpcRemoteError):
            return False
        return bool(r.get("supported"))

    def poll(self) -> List[Completion]:
        out, self._pending = self._pending, []
        for comp in out:
            self.outstanding.pop(comp.rid, None)
        return out

    def poll_chunks(self) -> List[TokenChunk]:
        """TokenChunks folded from worker frames since the last call
        (consume-once) — the streaming twin of poll(), same shape as
        the in-process ReplicaHandle's."""
        out, self._pending_chunks = self._pending_chunks, []
        return out

    def evacuate(self) -> List[tuple]:
        # a rid whose COMPLETION already surfaced (the final stream
        # drain beat the failover) finalizes through poll() — salvaging
        # it TOO would deliver prefix + full tokens, a double-count
        done = {c.rid for c in self._pending}
        out = [
            (st["req"], list(st["tokens"]), st["ftt"], st["phases"])
            for rid, st in self.outstanding.items() if rid not in done
        ]
        self.outstanding.clear()
        self._unconfirmed.clear()  # salvage owns the rids now
        return out

    def shed_queued(self, min_priority: int,
                    covers=None, tenants=None) -> List[int]:
        """`covers` (a callable) cannot cross the wire — the remote
        form of a tenant-scoped shed is the `tenants` name list, which
        the worker matches against folded tenant labels. None = shed
        every priority-eligible waiter (the global brown-out)."""
        c = self._client()
        if c is None:
            return []
        try:
            kw = {} if tenants is None else {"tenants": list(tenants)}
            r = c.call("shed", min_priority=min_priority, **kw)
        except (RpcError, RpcRemoteError):
            self._broken = True
            return []
        for rid in r["rids"]:
            self.outstanding.pop(rid, None)
            self._shed_skip.add(rid)
        return list(r["rids"])

    def begin_drain(self) -> None:
        """Handle-side half of a scale-down: stop offering this replica
        to dispatch NOW (before the worker's first refusal can round
        trip) and remember that a coming death is a retirement. The
        process-side half — rpc drain + SIGTERM — is
        `Supervisor.shrink()`."""
        self._drain_requested = True
        self._remote_draining = True

    # ------------------------------------------------------- observables
    @property
    def kv_summary(self) -> Optional[dict]:
        """The worker's last-heartbeat KV/radix-cache summary (blocks
        in use, prefix hit rate, evictable count) — None until a stats
        frame carried one. Federated into per-worker gauges by
        fleet_targets/ScrapeFederator; the groundwork for cache-aware
        routing."""
        return self._stats.get("kv")

    @property
    def load(self) -> float:
        # `outstanding` is this handle's live work SYNCHRONOUSLY (the
        # polled stats lag one heartbeat — a submit burst between polls
        # would otherwise all pile onto the same replica)
        return float(max(
            len(self.outstanding),
            self._stats.get("queue", 0) + self._stats.get("active", 0),
        ))

    @property
    def has_queue_space(self) -> bool:
        if self._remote_draining:
            return False   # drain refusals are certain — stop offering
        return len(self.outstanding) < self._max_queue + self._max_slots

    @property
    def max_slots(self) -> int:
        return self._stats.get("max_slots", self._max_slots)

    @property
    def queue_len(self) -> int:
        return self._stats.get("queue", 0)

    @property
    def active(self) -> int:
        return self._stats.get("active", 0)

    def fits_prompt(self, n_tokens: int) -> bool:
        # conservative client-side mirror of engine.bucket_for — the
        # client knows the spec's buckets (it wrote them)
        return n_tokens <= self._max_bucket

    def stream_fileno(self) -> Optional[int]:
        """Push-stream fd for select()-driven drive loops (None while
        the stream is down — callers fall back to a timed nap)."""
        if self._stream is None:
            return None
        try:
            return self._stream.fileno()
        except OSError:
            return None

    def heartbeat_age(self, now: Optional[float] = None) -> Optional[float]:
        if self._last_heartbeat is None:
            return None
        now = self.clock.now() if now is None else now
        return max(0.0, now - self._last_heartbeat)

    # --------------------------------------------------------- lifecycle
    def probe_ok(self, now: float) -> bool:
        """Health probe for re-admission: a NEW process exists AND
        answers a ping. The router's breaker gates how often this runs
        (half-open backoff)."""
        self.supervisor.poll(now)
        c = self._client()
        if c is None:
            return False
        t0 = self.clock.now()
        try:
            r = c.call("ping", timeout_s=self.poll_timeout_s, retries=0)
        except (RpcError, RpcRemoteError):
            return False
        self._clock_sample(r, t0, self.clock.now())
        return True

    def restart(self) -> None:
        """Join a freshly probed process. Usually that is a NEW
        incarnation (fresh completions list -> watermark 0), but after
        a transport-blip 'death' the SAME process may still be alive —
        then the rpc `reset` drops its stale work (already
        re-dispatched on survivors; letting it finish would
        double-spend the engine) and hands back the completions
        watermark, so the client resyncs instead of replaying the
        whole history against possibly-reused rids. Heartbeat clock
        restarts; outstanding was already evacuated at death."""
        self.consumed = 0
        self.chunks_consumed = 0
        self._pending_chunks.clear()   # old incarnation's, if any
        c = self._client()
        if c is not None:
            try:
                r = c.call("reset", timeout_s=self.poll_timeout_s,
                           retries=0)
                self.consumed = int(r.get("completions", 0))
                self.chunks_consumed = int(r.get("chunks", 0))
            except (RpcError, RpcRemoteError):
                pass  # probe_ok just passed; a blip here resolves via
                #       the normal poll path (worst case: a fresh
                #       process replays nothing anyway)
        self._stats = {}           # also drops any cached digest: a
        #                            fresh radix publishes a new epoch
        self._unconfirmed.clear()  # old incarnation's casts are moot
        self._remote_draining = False
        self.last_submit_refused = False
        self._pub_version = None   # a fresh process numbers its own
        #                            snapshots — never alias the old one's
        self._drop_stream()        # re-subscribes to the NEW process
        self._shed_skip.clear()    # the old process's stream died with it
        if self.trace_collector is not None:
            # new incarnation = new trace-frame numbering AND a new
            # clock domain: re-measure the offset from scratch — NOW,
            # while the freshly probed worker is still idle (tight RTT)
            self.trace_collector.on_worker_restart(self.id)
            self.measure_clock()
        self._last_heartbeat = self.clock.now()
        self._broken = False

    def warmup(self, widths=None) -> None:
        pass  # workers warm before READY; nothing to do from here

    def compile_stats(self) -> dict:
        return self._stats.get("compile_stats", {})


# ------------------------------------------------------------ fleet builder
def make_fleet_router(
    base_spec: WorkerSpec,
    n_workers: int,
    *,
    clock=None,
    config=None,
    sup_config: SupervisorConfig = SupervisorConfig(),
    registry=None,
    tracer=None,
    slo=None,
    telemetry=None,
    ledger=None,
    heartbeat_timeout_s: float = 2.0,
    spawn_fn: Optional[Callable] = None,
):
    """Spawn `n_workers` worker processes from `base_spec` (replica ids
    stamped per slot) and build a Router over their RemoteReplicaHandles
    — the cross-process mirror of serve/router.py `make_router`.
    Returns (router, supervisor, handles); the caller owns
    `supervisor.stop()` (use `with supervisor:`)."""
    from ddp_practice_tpu.serve.metrics import RouterMetrics
    from ddp_practice_tpu.serve.router import Router, RouterConfig

    clock = clock or MonotonicClock()
    specs = [
        dataclasses.replace(base_spec, replica=i) for i in range(n_workers)
    ]
    collector = None
    if tracer is not None and base_spec.trace:
        # the fleet trace plane: workers record + stream their spans
        # (spec.trace), the collector merges them into THIS recorder
        # under worker-N lanes with measured clock offsets applied
        from ddp_practice_tpu.utils.trace import TraceCollector

        collector = TraceCollector(tracer, registry=registry)
        for i in range(n_workers):
            collector.label_worker(
                i, specs[i].engine.get("max_slots", 4))
        if (base_spec.trace_sample < 1.0
                or base_spec.trace_keep_slow_s is not None
                or base_spec.trace_tenant_rates):
            # the fleet-side half of the coherent-sampling contract:
            # the router stamps one head decision per trace_id with the
            # SAME hash (and the same per-tenant override table) the
            # workers use, so both ends of the RPC seam agree without
            # ever exchanging a verdict
            from ddp_practice_tpu.utils.trace import TraceSampler

            tracer.set_sampler(
                TraceSampler(base_spec.trace_sample,
                             keep_slow_s=base_spec.trace_keep_slow_s,
                             tenant_rates=base_spec.trace_tenant_rates),
                registry=registry,
            )
    supervisor = Supervisor(specs, sup_config, spawn_fn=spawn_fn,
                            clock=clock)
    supervisor.start()
    handles = [
        RemoteReplicaHandle(
            i, supervisor, specs[i], clock=clock,
            heartbeat_timeout_s=heartbeat_timeout_s,
            trace_collector=collector,
        )
        for i in range(n_workers)
    ]
    if collector is not None:
        for h in handles:
            h.measure_clock()  # tight offsets BEFORE any traffic
    router = Router(
        handles, clock=clock, config=config or RouterConfig(),
        metrics=RouterMetrics(registry), tracer=tracer,
        slo=slo, telemetry=telemetry, ledger=ledger,
    )
    router.trace_collector = collector
    return router, supervisor, handles


def make_federated_server(supervisor: Supervisor,
                          handles, *,
                          port: int = 0, stale_after_s: float = 5.0,
                          autoscaler=None):
    """One fleet-level TelemetryServer over every worker's endpoints:
    /metrics re-labels each worker's exposition with worker="N" plus
    fleet_worker_up / heartbeat-age / restart series, /healthz renders
    the verdict tools/check_fleet.py judges, /flight rolls the workers'
    latency windows into true fleet percentiles (pooled samples, shared
    percentile_summary). Returns (federator, server); caller owns
    server.close().

    `handles` may be a list OR a zero-arg callable returning the
    CURRENT handle list. The callable form is what an elastic fleet
    needs: the federator re-resolves targets on every scrape, so a
    slot promoted or drained mid-run appears/disappears from the
    federated views instead of going stale (slot ids are stable, so
    every label minted for worker="N" stays true). With `autoscaler`
    set, /healthz carries its state block (size/min/max, standby
    depth, last scale event) for tools/check_fleet.py."""
    from ddp_practice_tpu.utils.telemetry import (
        ScrapeFederator,
        TelemetryServer,
    )

    handles_fn = handles if callable(handles) else (lambda: handles)
    fed = ScrapeFederator(
        lambda: fleet_targets(supervisor, handles_fn()),
        stale_after_s=stale_after_s,
        autoscaler_fn=(autoscaler.snapshot
                       if autoscaler is not None else None),
    )
    server = TelemetryServer(registry=fed, healthz_fn=fed.healthz,
                             flight_fn=fed.flight, port=port)
    return fed, server


def fleet_targets(supervisor: Supervisor,
                  handles: List[RemoteReplicaHandle]) -> Dict[int, dict]:
    """The scrape federator's view of the fleet: per slot, where the
    worker's telemetry endpoints live and how fresh its heartbeat is
    (utils/telemetry.py ScrapeFederator consumes this). Keyed by the
    handle's STABLE slot id — an elastic fleet appends slots and
    tombstones shrunk ones, so ids never alias across scale events."""
    out: Dict[int, dict] = {}
    for h in handles:
        w = supervisor.worker(h.id)
        out[h.id] = {
            "host": "127.0.0.1",
            "port": w.telemetry_port if w is not None else None,
            "pid": w.pid if w is not None else None,
            "up": w is not None,
            "state": supervisor.state(h.id),
            "draining": supervisor.draining(h.id),
            "restarts": supervisor.restarts[h.id],
            "heartbeat_age_s": h.heartbeat_age(),
            "kv": h.kv_summary,
        }
    return out
